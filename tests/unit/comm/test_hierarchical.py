"""Tier-1 gates for the hierarchical mesh collectives
(``comm/hierarchical.py``): mesh-spec construction/validation, the
axis-selective long-haul quantization contract (own-coordinate rows
bit-exact, crossing rows dequantized, EF residuals pinned to zero on
the own slice), per-mesh-axis wire-byte attribution, and the matched
quantized/unquantized-equiv byte pairs. Full-width bitwise parity vs
native and the flat rings lives in ``test_ring.py``
(``TestGroupedMultiAxis``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from hcache_deepspeed_tpu.comm.comms_logging import get_comms_logger
from hcache_deepspeed_tpu.comm.hierarchical import (
    axis_groups, hierarchical_all_gather, hierarchical_all_reduce_sum,
    hierarchical_reduce_scatter_sum, make_mesh_spec, validate_mesh_spec)
from hcache_deepspeed_tpu.ops.quantizer import dequantize, quantize
from hcache_deepspeed_tpu.runtime.config import HDSConfigError


def _mesh(n):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} virtual devices")
    return Mesh(np.array(devs[:n]).reshape(n), ("d",))


def _shm(mesh, f, ins, outs):
    return jax.jit(jax.shard_map(f, mesh=mesh, in_specs=ins,
                                 out_specs=outs, check_vma=False))


class TestMeshSpec:

    def test_defaults_2d(self):
        spec = make_mesh_spec([2, 4])
        assert spec.names == ("inter", "intra")
        assert spec.longhaul == "inter"
        assert spec.longhaul_dim == 0
        assert spec.world == 8
        assert spec.describe()["shape"] == [2, 4]

    def test_axis_groups_match_rank_factoring(self):
        # 2x4 row-major: inner groups contiguous, outer groups strided
        assert axis_groups((2, 4), 1) == [[0, 1, 2, 3], [4, 5, 6, 7]]
        assert axis_groups((2, 4), 0) == [[0, 4], [1, 5], [2, 6], [3, 7]]

    def test_bandwidths_ride_the_spec(self):
        spec = make_mesh_spec([2, 4], link_gbytes_per_s=[6.75, 45.0])
        assert spec.bandwidths() == {"inter": 6.75, "intra": 45.0}

    def test_degenerate_shapes_rejected(self):
        with pytest.raises(HDSConfigError, match="at least 2 axes"):
            make_mesh_spec([8])
        with pytest.raises(HDSConfigError, match="size >= 2"):
            make_mesh_spec([8, 1])
        with pytest.raises(HDSConfigError, match="duplicate"):
            make_mesh_spec([2, 4], axis_names=["x", "x"])
        with pytest.raises(HDSConfigError, match="match"):
            make_mesh_spec([2, 4], axis_names=["x"])
        with pytest.raises(HDSConfigError, match="unknown"):
            make_mesh_spec([2, 4], longhaul_axis="dcn")
        with pytest.raises(HDSConfigError, match="per-axis bandwidth"):
            make_mesh_spec([2, 4], link_gbytes_per_s=[1.0])

    def test_world_and_bits_validation(self):
        spec = make_mesh_spec([2, 4])
        validate_mesh_spec(spec, world_size=8, longhaul_bits=4)
        with pytest.raises(HDSConfigError, match="factor the axis"):
            validate_mesh_spec(spec, world_size=16)
        with pytest.raises(HDSConfigError, match="wire_bits"):
            validate_mesh_spec(spec, world_size=8, longhaul_bits=16)


class TestLonghaulQuantizedGather:
    """The axis-selective contract: rows from this device's own
    long-haul coordinate arrive BIT-EXACT (they never crossed the slow
    wire); every other row is the dequantized form of the source's
    intra-gathered block — genuinely lossy (not the exact values) but
    within the int8/int4 groupwise error envelope. The dequant value is
    checked against an eagerly-computed reference to ~1 ulp (XLA may
    re-associate the identical multiply inside the compiled program, so
    bit-for-bit is the wrong assertion for the crossing rows)."""

    @pytest.mark.parametrize("bits", (8, 4))
    def test_exact_vs_dequant_pattern(self, eight_devices, bits):
        mesh = _mesh(8)
        spec = make_mesh_spec([2, 4])
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(8, 13)), jnp.float32)

        def hq(xl):
            return hierarchical_all_gather(
                xl[0], "d", spec, longhaul_bits=bits,
                group_size=16)[None]

        got = np.asarray(_shm(mesh, hq, (P("d"),), P("d"))(x))
        full = np.asarray(x)
        from hcache_deepspeed_tpu.runtime.zero.qwire import (pack_int4,
                                                             unpack_int4)
        for r in range(8):
            o = r // 4
            for s in range(8):
                so = s // 4
                if so == o:
                    # fast-axis rows: bit-exact, no quantization ever
                    np.testing.assert_array_equal(got[r, s], full[s])
                else:
                    # source (so, *) quantized its intra-gathered
                    # [4, 13] block as one payload
                    blk = jnp.asarray(full[so * 4:(so + 1) * 4])
                    q, sc, sh, ct = quantize(
                        blk, group_size=16,
                        num_bits=4 if bits == 4 else 8)
                    if bits == 4:
                        q = unpack_int4(pack_int4(q), q.shape[-1])
                    deq = np.asarray(dequantize(q, sc, sh, ct))
                    np.testing.assert_allclose(got[r, s], deq[s % 4],
                                               rtol=1e-6, atol=1e-6)
            # the crossing block as a whole really was quantized —
            # it must NOT equal the exact values
            other = 1 - o
            assert not np.array_equal(
                got[r, other * 4:(other + 1) * 4],
                full[other * 4:(other + 1) * 4])

    def test_longhaul_pair_logged(self, eight_devices):
        mesh = _mesh(8)
        spec = make_mesh_spec([2, 4])
        logger = get_comms_logger()
        logger.configure(enabled=True)
        logger.reset()
        x = jnp.asarray(np.random.default_rng(1).normal(size=(8, 64)),
                        jnp.float32)

        def hq(xl):
            return hierarchical_all_gather(
                xl[0], "d", spec, longhaul_bits=8,
                op_name="t_hier_ag")[None]

        _shm(mesh, hq, (P("d"),), P("d"))(x)
        savings = logger.wire_savings_summary()
        assert "t_hier_ag_longhaul" in savings, savings
        rec = savings["t_hier_ag_longhaul"]
        # int8 + fp32 group scales: well under half of fp32 full width
        assert rec["fraction"] < 0.5
        # per-axis attribution: intra full-width, inter quantized
        per_axis = logger.permute_axis_bytes()["t_hier_ag"]
        assert set(per_axis) == {"intra", "inter"}
        # intra phase: 3 neighbor sends x 64 fp32 per trace
        assert per_axis["intra"] == 3 * 64 * 4
        # inter phase ships payload+scales (int8-dominated): fewer
        # bytes than the full-width equivalent (1 send x intra block)
        assert per_axis["inter"] < 4 * 64 * 4
        totals = logger.total_axis_bytes()
        assert totals["intra"] == per_axis["intra"]
        assert totals["inter"] == per_axis["inter"]
        logger.reset()
        logger.configure(enabled=False)


class TestLonghaulQuantizedReduce:

    @pytest.mark.parametrize("bits", (8, 4))
    def test_close_to_native_and_ef_improves(self, eight_devices, bits):
        """Quantized long-haul reduce: close to the native sum within
        the groupwise error envelope, and CUMULATIVE error over
        repeated residual-threaded passes stays bounded (the 1-bit /
        EF contract: the error is re-injected, not compounded — without
        EF the same deterministic bias repeats every pass). The
        own-coordinate slice of the residual is pinned to zero — that
        block shipped exact."""
        mesh = _mesh(8)
        spec = make_mesh_spec([2, 4])
        rng = np.random.default_rng(2)
        w = jnp.asarray(rng.normal(size=(8, 16, 3)), jnp.float32)
        steps = 4

        def hq(wl):
            outs, res = [], None
            for _ in range(steps):
                out, res = hierarchical_reduce_scatter_sum(
                    wl[0], "d", spec, longhaul_bits=bits, residual=res)
                outs.append(out)
            return tuple(outs) + (res,)

        f = jax.jit(jax.shard_map(
            hq, mesh=mesh, in_specs=(P("d"),),
            out_specs=tuple([P("d")] * (steps + 1)), check_vma=False))
        *outs, res_last = f(w)
        ref = np.asarray(_shm(mesh, lambda wl: jax.lax.psum_scatter(
            wl[0], "d", scatter_dimension=0, tiled=True),
            (P("d"),), P("d"))(w))
        # 4 of the 8 contributions per output element cross the long
        # haul; each carries up to scale/2 = absmax/(2*qmax) error
        absmax = float(np.abs(np.asarray(w)).max())
        qmax = 127 if bits == 8 else 7
        tol = 4 * absmax / (2 * qmax) * 1.1
        assert np.allclose(np.asarray(outs[0]), ref, atol=tol)
        # cumulative EF error << repeating the first pass's bias
        cum_ef = np.abs(sum(np.asarray(o) for o in outs)
                        - steps * ref).sum()
        cum_noef = steps * np.abs(np.asarray(outs[0]) - ref).sum()
        assert cum_ef < cum_noef
        # own-coordinate residual slice is zero on every device: the
        # global stacked view [8 * 2, W] interleaves devices' [2, W]
        # residuals; device (o, i)'s own row o must be zero
        res = np.asarray(res_last).reshape(8, 2, -1)
        for dev in range(8):
            own = dev // 4
            assert np.all(res[dev, own] == 0.0)
            assert np.any(res[dev, 1 - own] != 0.0)

    def test_plain_signature_unchanged(self, eight_devices):
        """Without longhaul_bits the return is the flat-ring signature
        (no residual tuple) — pinned so transport swaps stay drop-in."""
        mesh = _mesh(8)
        spec = make_mesh_spec([2, 4])
        w = jnp.asarray(np.random.default_rng(3).normal(size=(8, 8, 2)),
                        jnp.float32)

        def hier(wl):
            return hierarchical_reduce_scatter_sum(wl[0], "d", spec)

        out = np.asarray(_shm(mesh, hier, (P("d"),), P("d"))(w))
        # local [m=1, 2] shards stack to [8, 2] under P("d")
        assert out.shape == (8, 2)


class TestAllReduceAndAttribution:

    def test_all_reduce_bitwise_vs_flat(self, eight_devices):
        from hcache_deepspeed_tpu.comm.ring import ring_all_reduce_sum
        mesh = _mesh(8)
        spec = make_mesh_spec([2, 4])
        x = jnp.asarray(np.random.default_rng(4).normal(size=(8, 7, 5)),
                        jnp.float32)

        def hier(xl):
            return hierarchical_all_reduce_sum(xl[0], "d", spec)[None]

        def flat(xl):
            return ring_all_reduce_sum(xl[0], "d")[None]

        a = np.asarray(_shm(mesh, hier, (P("d"),), P("d"))(x))
        b = np.asarray(_shm(mesh, flat, (P("d"),), P("d"))(x))
        np.testing.assert_array_equal(a, b)

    def test_per_axis_bytes_split_the_flat_bucket(self, eight_devices):
        """The satellite contract: permute bytes are attributable per
        mesh-axis name, intra- vs inter-axis separately queryable, and
        the per-op totals still reconcile with the lumped summary."""
        mesh = _mesh(8)
        spec = make_mesh_spec([2, 4])
        logger = get_comms_logger()
        logger.configure(enabled=True)
        logger.reset()
        x = jnp.asarray(np.random.default_rng(5).normal(size=(8, 40)),
                        jnp.float32)

        def hier(xl):
            return hierarchical_all_gather(
                xl[0], "d", spec, op_name="t_axis_ag")[None]

        _shm(mesh, hier, (P("d"),), P("d"))(x)
        per_axis = logger.permute_axis_bytes()["t_axis_ag"]
        # intra ring: 3 sends x 40 fp32; inter ring: 1 send x the
        # intra-gathered [4, 40] block
        assert per_axis == {"intra": 3 * 40 * 4, "inter": 1 * 4 * 40 * 4}
        lumped = logger.permute_bytes_summary()["t_axis_ag"]
        assert lumped == sum(per_axis.values())
        logger.reset()
        logger.configure(enabled=False)


class TestPhasePipelining:
    """ISSUE 15: ``pipeline_chunks > 1`` splits every payload into
    column chunks riding independent full phase chains — chunk k's
    long-haul phase structurally independent of chunk k+1's intra
    phase. Pure data movement: bitwise-equal to the unpipelined form
    AND to native at any chunk count (uneven splits included)."""

    @pytest.mark.parametrize("dtype", (jnp.float32, jnp.bfloat16),
                             ids=lambda d: d.__name__)
    @pytest.mark.parametrize("pc", (2, 3))
    def test_pipelined_gather_bitwise(self, eight_devices, dtype, pc):
        mesh = _mesh(8)
        spec = make_mesh_spec([2, 4])
        rng = np.random.default_rng(10)
        x = jnp.asarray(rng.normal(size=(8, 37)), dtype)

        def piped(xl):
            return hierarchical_all_gather(xl[0], "d", spec,
                                           pipeline_chunks=pc)[None]

        def native(xl):
            return jax.lax.all_gather(xl[0], "d")[None]

        a = np.asarray(_shm(mesh, piped, (P("d"),), P("d"))(x))
        b = np.asarray(_shm(mesh, native, (P("d"),), P("d"))(x))
        np.testing.assert_array_equal(a.astype(np.float32),
                                      b.astype(np.float32))

    @pytest.mark.parametrize("dtype", (jnp.float32, jnp.bfloat16),
                             ids=lambda d: d.__name__)
    @pytest.mark.parametrize("pc", (2, 3))
    def test_pipelined_reduce_scatter_bitwise(self, eight_devices,
                                              dtype, pc):
        mesh = _mesh(8)
        spec = make_mesh_spec([2, 4])
        rng = np.random.default_rng(11)
        wide = jnp.asarray(rng.normal(size=(8, 8, 21)), dtype)

        def piped(w):
            return hierarchical_reduce_scatter_sum(
                w[0], "d", spec, pipeline_chunks=pc)

        def native(w):
            return jax.lax.psum_scatter(w[0], "d",
                                        scatter_dimension=0, tiled=True)

        a = np.asarray(_shm(mesh, piped, (P("d"),), P("d"))(wide))
        b = np.asarray(_shm(mesh, native, (P("d"),), P("d"))(wide))
        np.testing.assert_array_equal(a.astype(np.float32),
                                      b.astype(np.float32))

    def test_pipelined_cross_axis_structure(self, eight_devices):
        """The structural claim itself, on the compiled module: the
        unpipelined gather has ZERO dependence-free cross-axis permute
        pairs (every long-haul permute descends from every intra
        permute); the pipelined form has them, one per co-resident
        chunk pair."""
        from hcache_deepspeed_tpu.profiling.hlo_audit import \
            audit_compiled
        mesh = _mesh(8)
        spec = make_mesh_spec([2, 4])
        x = jnp.ones((8, 64), jnp.float32)
        reps = {}
        for pc in (1, 2):
            def f(xl, pc=pc):
                return hierarchical_all_gather(
                    xl[0], "d", spec, pipeline_chunks=pc)[None]
            compiled = jax.jit(jax.shard_map(
                f, mesh=mesh, in_specs=(P("d"),), out_specs=P("d"),
                check_vma=False)).lower(x).compile()
            reps[pc] = audit_compiled(compiled)
        assert reps[1].cross_axis["pairs"] == 0
        assert reps[1].cross_axis_overlap_ratio() == 0.0
        assert reps[2].cross_axis["pairs"] >= 1
        assert reps[2].cross_axis_overlap_ratio() > 0.0

    @pytest.mark.parametrize("bits", (8, 4))
    def test_pipelined_longhaul_reduce_residual_layout(
            self, eight_devices, bits):
        """Quantized long-haul reduce under pipelining: per-chunk
        quantization is deterministic and SELF-CONSISTENT — the
        residual columns follow the chunk-concatenation layout, so a
        residual produced by one pipelined pass feeds the next pass's
        identical chunk split, and the EF contract (own-coordinate
        slice zero) holds per chunk."""
        mesh = _mesh(8)
        spec = make_mesh_spec([2, 4])
        rng = np.random.default_rng(12)
        w = jnp.asarray(rng.normal(size=(8, 16, 3)), jnp.float32)

        def hq(wl):
            out1, res1 = hierarchical_reduce_scatter_sum(
                wl[0], "d", spec, longhaul_bits=bits,
                pipeline_chunks=3)
            out2, res2 = hierarchical_reduce_scatter_sum(
                wl[0], "d", spec, longhaul_bits=bits,
                pipeline_chunks=3, residual=res1)
            return out1, out2, res1, res2

        out1, out2, res1, res2 = jax.jit(jax.shard_map(
            hq, mesh=mesh, in_specs=(P("d"),),
            out_specs=(P("d"), P("d"), P("d"), P("d")),
            check_vma=False))(w)
        ref = np.asarray(_shm(mesh, lambda wl: jax.lax.psum_scatter(
            wl[0], "d", scatter_dimension=0, tiled=True),
            (P("d"),), P("d"))(w))
        absmax = float(np.abs(np.asarray(w)).max())
        qmax = 127 if bits == 8 else 7
        tol = 4 * absmax / (2 * qmax) * 1.1
        assert np.allclose(np.asarray(out1), ref, atol=tol)
        assert np.allclose(np.asarray(out2), ref, atol=tol)
        # residual shapes stable across passes (the chunk-concat
        # layout is deterministic), own-coordinate slices zero
        assert np.asarray(res1).shape == np.asarray(res2).shape
        res = np.asarray(res1).reshape(8, 2, -1)
        for dev in range(8):
            own = dev // 4
            assert np.all(res[dev, own] == 0.0)


class TestUnifiedHpzTier:
    """ISSUE 15 tentpole: ``hpz`` maps onto the mesh's innermost axes
    — the hpZ gather becomes grouped ring phases over exactly the mesh
    axes the hpZ box covers, bitwise-equal to the native grouped
    gather over hpz consecutive ranks."""

    @pytest.mark.parametrize("dtype", (jnp.float32, jnp.bfloat16),
                             ids=lambda d: d.__name__)
    @pytest.mark.parametrize("hpz", (2, 4, 8))
    def test_tier_gather_bitwise_vs_native_groups(self, eight_devices,
                                                  dtype, hpz):
        mesh = _mesh(8)
        spec = make_mesh_spec([2, 4])
        rng = np.random.default_rng(13)
        x = jnp.asarray(rng.normal(size=(8, 23)), dtype)
        groups = [list(range(g * hpz, (g + 1) * hpz))
                  for g in range(8 // hpz)]

        def tier(xl):
            return hierarchical_all_gather(xl[0], "d", spec,
                                           hpz=hpz)[None]

        def native(xl):
            return jax.lax.all_gather(xl[0], "d",
                                      axis_index_groups=groups)[None]

        a = np.asarray(_shm(mesh, tier, (P("d"),), P("d"))(x))
        b = np.asarray(_shm(mesh, native, (P("d"),), P("d"))(x))
        np.testing.assert_array_equal(a.astype(np.float32),
                                      b.astype(np.float32))

    @pytest.mark.parametrize("bits", (8, 4))
    def test_tier_spanning_longhaul_quantizes_crossings(
            self, eight_devices, bits):
        """hpz=8 on a 2x4 mesh covers BOTH axes: the tier's inter
        phase is a real long-haul phase, so longhaul_bits applies —
        own-coordinate rows exact, crossing rows dequantized (int8 and
        nibble-packed int4)."""
        mesh = _mesh(8)
        spec = make_mesh_spec([2, 4])
        rng = np.random.default_rng(14)
        x = jnp.asarray(rng.normal(size=(8, 13)), jnp.float32)

        def hq(xl):
            return hierarchical_all_gather(
                xl[0], "d", spec, hpz=8, longhaul_bits=bits,
                group_size=16)[None]

        got = np.asarray(_shm(mesh, hq, (P("d"),), P("d"))(x))
        full = np.asarray(x)
        for r in range(8):
            o = r // 4
            np.testing.assert_array_equal(
                got[r, o * 4:(o + 1) * 4], full[o * 4:(o + 1) * 4])
            assert not np.array_equal(
                got[r, (1 - o) * 4:(2 - o) * 4],
                full[(1 - o) * 4:(2 - o) * 4])

    def test_tier_gather_attributes_only_covered_axes(
            self, eight_devices):
        """hpz=4 covers ONLY the intra axis: per-axis permute bytes
        must show intra traffic and ZERO inter traffic — the whole
        point of the tier (per-micro gathers never touch the slow
        wire)."""
        mesh = _mesh(8)
        spec = make_mesh_spec([2, 4])
        logger = get_comms_logger()
        logger.configure(enabled=True)
        logger.reset()
        x = jnp.asarray(np.random.default_rng(15).normal(size=(8, 40)),
                        jnp.float32)

        def tier(xl):
            return hierarchical_all_gather(
                xl[0], "d", spec, hpz=4, op_name="t_hpz_ag")[None]

        _shm(mesh, tier, (P("d"),), P("d"))(x)
        per_axis = logger.permute_axis_bytes()["t_hpz_ag"]
        assert set(per_axis) == {"intra"}, per_axis
        assert per_axis["intra"] == 3 * 40 * 4
        logger.reset()
        logger.configure(enabled=False)


class TestPodScaleSpecBookkeeping:
    """The 256-device (16x16) spec-level construction gate (ISSUE 15):
    group/coordinate/chunk bookkeeping at the BASELINE.json v5e-256
    factoring, pure host-side — no device arrays materialize (tier-1
    safe on an 8-device CPU harness)."""

    def test_16x16_groups_and_coords(self):
        from hcache_deepspeed_tpu.comm.hierarchical import (
            _gather_phases, validate_mesh_spec)
        spec = make_mesh_spec([16, 16],
                              link_gbytes_per_s=[6.75, 45.0])
        assert spec.world == 256
        validate_mesh_spec(spec, world_size=256, longhaul_bits=8)
        inter = axis_groups(spec.sizes, 0)
        intra = axis_groups(spec.sizes, 1)
        assert len(inter) == 16 and len(intra) == 16
        assert all(len(g) == 16 for g in inter + intra)
        # intra rows contiguous, inter columns strided by 16
        assert intra[0] == list(range(16))
        assert inter[0] == list(range(0, 256, 16))
        # every rank appears exactly once per dim's groups
        for groups in (inter, intra):
            flat = sorted(r for g in groups for r in g)
            assert flat == list(range(256))
        phases = _gather_phases(spec)
        assert [dim for dim, _, _ in phases] == [1, 0]  # inner first
        assert [span for _, _, span in phases] == [16, 16]

    def test_16x16_hpz_tiers(self):
        from hcache_deepspeed_tpu.comm.hierarchical import (
            axis_subgroups, hpz_tier_dims)
        from hcache_deepspeed_tpu.runtime.config import HDSConfigError
        spec = make_mesh_spec([16, 16])
        assert hpz_tier_dims(spec, 16) == [(1, 16)]
        assert hpz_tier_dims(spec, 4) == [(1, 4)]
        assert hpz_tier_dims(spec, 64) == [(1, 16), (0, 4)]
        assert hpz_tier_dims(spec, 256) == [(1, 16), (0, 16)]
        with pytest.raises(HDSConfigError, match="multiple"):
            hpz_tier_dims(spec, 24)    # 24 = 16*1.5: genuine mismatch
        sub = axis_subgroups((16, 16), 1, 4)
        assert len(sub) == 64 and all(len(g) == 4 for g in sub)
        assert sub[0] == [0, 1, 2, 3]
        # aligned runs: every subgroup stays inside one intra row
        assert all(g[0] // 16 == g[-1] // 16 for g in sub)

    def test_16x16_chunk_bookkeeping(self):
        """Pipeline chunk bounds + per-phase send counts at pod
        scale: the (K-1) ring sends per phase the wire-cost model
        assumes."""
        from hcache_deepspeed_tpu.comm.ring import _chunk_bounds
        bounds = _chunk_bounds(10_000_000, 4)
        assert bounds[0][0] == 0 and bounds[-1][1] == 10_000_000
        assert all(a < b for a, b in bounds)
        # uneven split keeps every element exactly once
        bounds = _chunk_bounds(257, 4)
        assert sum(b - a for a, b in bounds) == 257

    def test_16x16_pod_projection(self):
        """The configurable projection target (satellite): a 16x16
        pod-shape row prices both axes, records the assumption and
        the calibration source."""
        from hcache_deepspeed_tpu.profiling.hlo_audit import \
            pod_scale_wire_seconds
        out = pod_scale_wire_seconds(
            {"inter": 1000.0, "intra": 3000.0},
            {"inter": 2, "intra": 4}, {"inter": 16, "intra": 16},
            {"inter": 6.75, "intra": 45.0})
        assert out["scaled_axis_bytes"]["inter"] == 15000
        assert out["scaled_axis_bytes"]["intra"] == 15000
        assert out["pod_axis_sizes"] == {"inter": 16, "intra": 16}
        assert out["calibration"] == "declared"
        assert out["bottleneck_axis"] == "inter"
