"""Shape/contract validation of the measured wire calibration
(``comm/benchmark.py calibrate_mesh_axes``, ISSUE 15). On CPU the
GB/s numbers are physically meaningless — these tests pin the
STRUCTURE the wire-cost model consumes (per-axis rows, headline
bandwidths, declared-vs-measured divergence, the "measured"
calibration label), which is exactly what the committed
wire-calibration artifact phase gates. On chip the same entry point is
the ``bin/chip_overlap_campaign.sh`` calibration leg.
"""

import math

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from hcache_deepspeed_tpu.comm.benchmark import calibrate_mesh_axes
from hcache_deepspeed_tpu.comm.hierarchical import make_mesh_spec


def _mesh(n, axis="d"):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} virtual devices")
    return Mesh(np.array(devs[:n]).reshape(n), (axis,))


class TestCalibrateMeshAxes:

    def test_rows_and_headline_shape(self, eight_devices):
        spec = make_mesh_spec([2, 4], link_gbytes_per_s=[6.75, 45.0])
        cal = calibrate_mesh_axes(spec, mesh=_mesh(8), axis="d",
                                  payload_bytes=(1 << 12, 1 << 14),
                                  trials=2)
        assert cal["calibration"] == "measured"
        assert set(cal["gbytes_per_s"]) == {"inter", "intra"}
        assert all(math.isfinite(v) and v > 0
                   for v in cal["gbytes_per_s"].values())
        # one row per (axis, payload), each carrying both the measured
        # and the declared number — the in-row divergence evidence
        assert len(cal["rows"]) == 4
        for row in cal["rows"]:
            assert row["payload_bytes"] in (1 << 12, 1 << 14)
            assert row["seconds_per_round"] > 0
            assert row["declared_gbytes_per_s"] in (6.75, 45.0)
            assert row["rounds"] == row["axis_size"] - 1

    def test_divergence_vs_declared(self, eight_devices):
        spec = make_mesh_spec([2, 4], link_gbytes_per_s=[6.75, 45.0])
        cal = calibrate_mesh_axes(spec, mesh=_mesh(8), axis="d",
                                  payload_bytes=(1 << 12,), trials=1)
        div = cal["divergence_vs_declared"]
        assert set(div) == {"inter", "intra"}
        for axis, ratio in div.items():
            assert ratio == pytest.approx(
                cal["gbytes_per_s"][axis]
                / spec.bandwidths()[axis])

    def test_undeclared_bandwidth_divergence_is_none(self,
                                                     eight_devices):
        """No declared bandwidth => divergence None — visible, never
        silently dropped or faked."""
        spec = make_mesh_spec([2, 4])
        cal = calibrate_mesh_axes(spec, mesh=_mesh(8), axis="d",
                                  payload_bytes=(1 << 12,), trials=1)
        assert cal["divergence_vs_declared"] == {"inter": None,
                                                 "intra": None}

    def test_feeds_wire_cost_model_as_measured(self, eight_devices):
        from hcache_deepspeed_tpu.profiling.hlo_audit import \
            wire_cost_seconds
        spec = make_mesh_spec([2, 4], link_gbytes_per_s=[6.75, 45.0])
        cal = calibrate_mesh_axes(spec, mesh=_mesh(8), axis="d",
                                  payload_bytes=(1 << 12,), trials=1)
        cost = wire_cost_seconds({"inter": 1e6, "intra": 3e6},
                                 cal["gbytes_per_s"],
                                 calibration=cal["calibration"])
        assert cost["calibration"] == "measured"
        assert all(v["seconds"] is not None and v["seconds"] > 0
                   for v in cost["per_axis"].values())

    def test_too_few_devices_rejected(self):
        spec = make_mesh_spec([16, 16])
        with pytest.raises(ValueError, match="needs 256 devices"):
            calibrate_mesh_axes(spec)
