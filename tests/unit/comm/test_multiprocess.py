"""True multi-process rendezvous + data-parallel training.

The reference simulates multi-node with N local processes and a
file-store rendezvous (``tests/unit/common.py:129 DistributedExec``).
The TPU-native analog here is the real thing scaled down: two OS
processes, each owning one cpu device, rendezvous through
``jax.distributed`` (coordination service) with cross-process
collectives over gloo — exercising the exact code path a multi-host
TPU pod takes (``comm.init_distributed`` → ``jax.distributed.initialize``
→ global mesh spanning processes), which the in-process 8-device mesh
tests cannot reach.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "mp_worker.py")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_workers(port, timeout=420, zero_stage=0):
    """Spawn two ranks through the per-host launcher (torchrun-style env),
    exercising launcher.launch's env normalization on the way."""
    procs = []
    for rank in range(2):
        env = dict(os.environ,
                   PYTHONPATH=REPO,   # replaces the axon site dir: the
                   # workers must never touch the TPU relay
                   JAX_PLATFORMS="cpu",
                   XLA_FLAGS="",      # 1 cpu device per process (the
                   # conftest's 8-device flag would leak in otherwise)
                   HDS_TEST_ZERO_STAGE=str(zero_stage),
                   RANK=str(rank), WORLD_SIZE="2",
                   MASTER_ADDR="127.0.0.1", MASTER_PORT=str(port))
        env.pop("HDS_PROCESS_ID", None)
        env.pop("HDS_NUM_PROCESSES", None)
        env.pop("HDS_COORDINATOR_ADDRESS", None)
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "hcache_deepspeed_tpu.launcher.launch",
             WORKER],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=REPO))
    # drain both pipes concurrently: the ranks are lock-stepped by
    # collectives, so serially draining rank 0 while rank 1 fills its
    # 64KB pipe buffer would deadlock the pair
    import threading
    outs = [None, None]

    def drain(i):
        outs[i] = procs[i].communicate()[0]

    import time
    threads = [threading.Thread(target=drain, args=(i,)) for i in range(2)]
    try:
        deadline = time.monotonic() + timeout   # shared across both joins
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.1))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for t in threads:
            t.join(timeout=30)
    return procs, ["" if o is None else o for o in outs]


_REF_LOSSES = {}


def _single_process_reference():
    """The 3-step single-device trajectory on the same seed-7 batches —
    identical for every parametrization, so computed once per session."""
    if "losses" in _REF_LOSSES:
        return _REF_LOSSES["losses"]
    import jax

    import hcache_deepspeed_tpu as hds
    from hcache_deepspeed_tpu.models.gpt2 import GPT2LMHeadModel, gpt2_tiny
    from hcache_deepspeed_tpu.parallel import topology as topo_mod
    topo = topo_mod.initialize_topology(topo_mod.TopologySpec(data=1),
                                        devices=jax.devices()[:1])
    mcfg = gpt2_tiny()
    rng = np.random.default_rng(7)
    batches = [rng.integers(0, mcfg.vocab_size, (4, 16), dtype=np.int32)
               for _ in range(3)]
    engine, _, _, _ = hds.initialize(
        model=GPT2LMHeadModel(mcfg), topology=topo,
        example_batch={"input_ids": batches[0]},
        config={
            "train_batch_size": 4,
            "train_micro_batch_size_per_gpu": 4,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "steps_per_print": 10 ** 9,
        })
    _REF_LOSSES["losses"] = [
        float(engine.train_batch(batch={"input_ids": b})) for b in batches]
    topo_mod.reset_topology()
    return _REF_LOSSES["losses"]


def _parse_losses(out):
    losses = {}
    for line in out.splitlines():
        if line.startswith("LOSS "):
            _, rank, step, val = line.split()
            losses[int(step)] = float(val)
    return losses


@pytest.mark.slow
class TestMultiProcess:
    @pytest.mark.parametrize("zero_stage", [0, 3], ids=["dp", "zero3"])
    def test_two_process_dp_training_matches_single_process(self,
                                                            zero_stage):
        port = _free_port()
        procs, outs = _launch_workers(port, zero_stage=zero_stage)
        for p, out in zip(procs, outs):
            assert p.returncode == 0, out[-2000:]
        l0, l1 = (_parse_losses(o) for o in outs)
        assert set(l0) == set(l1) == {0, 1, 2}, (l0, l1)
        # both ranks observe the identical global loss (replicated) —
        # gradient sync drift would diverge them from step 1 on
        for step in range(3):
            assert l0[step] == pytest.approx(l1[step], rel=1e-6), (l0, l1)

        # and the 2-process run matches the same training done in one
        # process on the full global batch (loss parity across the
        # process boundary: collectives did exactly a mean over dp)
        for step, ref in enumerate(_single_process_reference()):
            # stage 3 reorders reductions (reduce-scatter + gather), so
            # its float tolerance is looser than plain dp allreduce
            tol = 2e-5 if zero_stage == 0 else 2e-4
            assert l0[step] == pytest.approx(ref, rel=tol), (
                step, l0[step], ref)
