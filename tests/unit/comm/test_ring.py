"""Tier-1 parity gates for the decomposed ring collectives
(``comm/ring.py``): every primitive must be BITWISE-equal to the native
collective it replaces — across world sizes 2/4/8, non-divisible chunk
counts, and fp32/bf16/int8 payloads — on the CPU ``jax.sharding`` mesh.
The bit-for-bit contract is what lets the layered ZeRO-3 step swap its
transport (``zero_collective_impl``) without perturbing a single
gradient; these tests are the primitive-level half of that gate (the
engine-level half lives in test_zero_overlap.py /
test_zeropp_prefetch.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from hcache_deepspeed_tpu.comm.comms_logging import get_comms_logger
from hcache_deepspeed_tpu.comm.ring import (decomposed_all_to_all_rows,
                                            decomposed_reduce_scatter_sum,
                                            ring_all_gather,
                                            ring_all_reduce_sum)

WORLD_SIZES = (2, 4, 8)
DTYPES = (jnp.float32, jnp.bfloat16, jnp.int8)


def _mesh(n):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} virtual devices")
    return Mesh(np.array(devs[:n]).reshape(n), ("d",))


def _shm(mesh, f, ins, outs):
    return jax.jit(jax.shard_map(f, mesh=mesh, in_specs=ins,
                                 out_specs=outs, check_vma=False))


def _payload(n, w, dtype, seed=0):
    rng = np.random.default_rng(seed)
    if jnp.issubdtype(dtype, jnp.integer):
        return jnp.asarray(rng.integers(-15, 15, size=(n, w)), dtype)
    return jnp.asarray(rng.normal(size=(n, w)), dtype)


class TestRingAllGather:

    @pytest.mark.parametrize("n", WORLD_SIZES)
    @pytest.mark.parametrize("dtype", DTYPES, ids=lambda d: d.__name__)
    @pytest.mark.parametrize("chunks", [1, 3])
    def test_bitwise_vs_native(self, n, dtype, chunks):
        """chunks=3 does not divide the 37-wide payload: uneven
        sub-chunk chains must reassemble exactly."""
        mesh = _mesh(n)
        x = _payload(n, 37, dtype)

        def ring(xl):
            return ring_all_gather(xl[0], "d", chunks=chunks)[None]

        def native(xl):
            return jax.lax.all_gather(xl[0], "d")[None]

        a = np.asarray(_shm(mesh, ring, (P("d"),), P("d"))(x))
        b = np.asarray(_shm(mesh, native, (P("d"),), P("d"))(x))
        np.testing.assert_array_equal(a, b)

    def test_grouped_matches_native_groups(self, eight_devices):
        """hpZ layout: intra-group rings must match the native
        axis_index_groups gather row for row."""
        mesh = _mesh(8)
        groups = [[0, 1, 2, 3], [4, 5, 6, 7]]
        x = _payload(8, 24, jnp.float32)

        def ring(xl):
            return ring_all_gather(xl[0], "d",
                                   axis_index_groups=groups)[None]

        def native(xl):
            return jax.lax.all_gather(xl[0], "d",
                                      axis_index_groups=groups)[None]

        a = np.asarray(_shm(mesh, ring, (P("d"),), P("d"))(x))
        b = np.asarray(_shm(mesh, native, (P("d"),), P("d"))(x))
        np.testing.assert_array_equal(a, b)

    def test_unequal_groups_rejected(self, eight_devices):
        mesh = _mesh(8)
        x = _payload(8, 8, jnp.float32)

        def ring(xl):
            return ring_all_gather(
                xl[0], "d", axis_index_groups=[[0, 1, 2], [3, 4, 5, 6, 7]]
            )[None]

        with pytest.raises(ValueError, match="equal-size"):
            _shm(mesh, ring, (P("d"),), P("d"))(x)


class TestDecomposedReduceScatter:

    @pytest.mark.parametrize("n", WORLD_SIZES)
    @pytest.mark.parametrize("dtype", DTYPES, ids=lambda d: d.__name__)
    @pytest.mark.parametrize("chunks", [1, 5])
    def test_bitwise_vs_psum_scatter(self, n, dtype, chunks):
        """The load-bearing claim: index-order fold + fp32 accumulation
        for sub-fp32 floats IS the native fold — bit for bit, so the
        decomposed reduce lane never changes a gradient."""
        mesh = _mesh(n)
        wide = _payload(n, n * 23, dtype).reshape(n, n, 23)

        def ring(w):
            return decomposed_reduce_scatter_sum(w[0], "d",
                                                 chunks=chunks)

        def native(w):
            return jax.lax.psum_scatter(w[0], "d",
                                        scatter_dimension=0, tiled=True)

        a = np.asarray(_shm(mesh, ring, (P("d"),), P("d"))(wide))
        b = np.asarray(_shm(mesh, native, (P("d"),), P("d"))(wide))
        np.testing.assert_array_equal(
            a.astype(np.float32).reshape(-1),
            b.astype(np.float32).reshape(-1))

    def test_tiled_multi_row_chunks(self, eight_devices):
        """[n*m, ...] inputs (m > 1): the _psum_scatter_mean_dim shape."""
        mesh = _mesh(8)
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.normal(size=(8, 16, 5)), jnp.float32)

        def ring(xl):
            return decomposed_reduce_scatter_sum(xl[0], "d")

        def native(xl):
            return jax.lax.psum_scatter(xl[0], "d",
                                        scatter_dimension=0, tiled=True)

        a = np.asarray(_shm(mesh, ring, (P("d"),), P("d"))(x))
        b = np.asarray(_shm(mesh, native, (P("d"),), P("d"))(x))
        np.testing.assert_array_equal(a, b)

    def test_indivisible_leading_dim_rejected(self, eight_devices):
        mesh = _mesh(8)
        x = jnp.ones((8, 9), jnp.float32)

        def ring(xl):
            return decomposed_reduce_scatter_sum(xl[0], "d")[None]

        with pytest.raises(ValueError, match="divisible"):
            _shm(mesh, ring, (P("d"),), P("d"))(x)


class TestDecomposedAllToAll:

    @pytest.mark.parametrize("n", WORLD_SIZES)
    @pytest.mark.parametrize("dtype", DTYPES, ids=lambda d: d.__name__)
    def test_bitwise_vs_all_to_all(self, n, dtype):
        """Source-order delivery: the quantized-wire transport swap
        (qwire/quantized_allreduce_body) relies on received rows being
        in exactly the native all_to_all layout."""
        mesh = _mesh(n)
        rows = _payload(n * n, 11, dtype, seed=4).reshape(n, n, 11)

        def ring(r):
            return decomposed_all_to_all_rows(r[0], "d")[None]

        def native(r):
            return jax.lax.all_to_all(r[0], "d", 0, 0)[None]

        a = np.asarray(_shm(mesh, ring, (P("d"),), P("d"))(rows))
        b = np.asarray(_shm(mesh, native, (P("d"),), P("d"))(rows))
        np.testing.assert_array_equal(a, b)


class TestRingAllReduce:

    @pytest.mark.parametrize("n", WORLD_SIZES)
    def test_matches_psum(self, n):
        """RS + AG composition over an awkward (pad-requiring) shape."""
        mesh = _mesh(n)
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.normal(size=(n, 7, 13)), jnp.float32)

        def ring(xl):
            return ring_all_reduce_sum(xl[0], "d")[None]

        def native(xl):
            return jax.lax.psum(xl[0], "d")[None]

        a = np.asarray(_shm(mesh, ring, (P("d"),), P("d"))(x))
        b = np.asarray(_shm(mesh, native, (P("d"),), P("d"))(x))
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)


class TestGroupedMultiAxis:
    """Satellite (ISSUE 12): grouped and uneven-chunk paths on
    multi-axis meshes — the 2x4 / 4x2 / 2x2x2 matrix over fp32/bf16 x
    divisible/uneven chunk counts, asserting bitwise equality with BOTH
    the flat-ring and the native results. These pin the grouped
    ``decomposed_all_to_all_rows`` generalization and the hierarchical
    composition built on it (``comm/hierarchical.py``)."""

    MESHES = ((2, 4), (4, 2), (2, 2, 2))
    #: chunks=1 divides every width below; chunks=3 does not (uneven
    #: numpy.array_split bounds must reassemble exactly)
    CHUNKS = (1, 3)

    @pytest.mark.parametrize("shape", MESHES, ids=str)
    @pytest.mark.parametrize("dtype", (jnp.float32, jnp.bfloat16),
                             ids=lambda d: d.__name__)
    @pytest.mark.parametrize("chunks", CHUNKS)
    def test_hier_all_gather_bitwise(self, eight_devices, shape, dtype,
                                     chunks):
        from hcache_deepspeed_tpu.comm.hierarchical import (
            hierarchical_all_gather, make_mesh_spec)
        mesh = _mesh(8)
        spec = make_mesh_spec(shape)
        x = _payload(8, 37, dtype)

        def hier(xl):
            return hierarchical_all_gather(xl[0], "d", spec,
                                           chunks=chunks)[None]

        def flat(xl):
            return ring_all_gather(xl[0], "d", chunks=chunks)[None]

        def native(xl):
            return jax.lax.all_gather(xl[0], "d")[None]

        a = np.asarray(_shm(mesh, hier, (P("d"),), P("d"))(x))
        b = np.asarray(_shm(mesh, native, (P("d"),), P("d"))(x))
        c = np.asarray(_shm(mesh, flat, (P("d"),), P("d"))(x))
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(a, c)

    @pytest.mark.parametrize("shape", MESHES, ids=str)
    @pytest.mark.parametrize("dtype", (jnp.float32, jnp.bfloat16),
                             ids=lambda d: d.__name__)
    @pytest.mark.parametrize("chunks", CHUNKS)
    def test_hier_reduce_scatter_bitwise(self, eight_devices, shape,
                                         dtype, chunks):
        """The load-bearing hierarchical claim: per-axis grouped
        delivery + destination source-index fold IS the native fold —
        the hierarchy only re-routes bytes, never re-associates the
        sum."""
        from hcache_deepspeed_tpu.comm.hierarchical import (
            hierarchical_reduce_scatter_sum, make_mesh_spec)
        mesh = _mesh(8)
        spec = make_mesh_spec(shape)
        wide = _payload(8, 8 * 21, dtype).reshape(8, 8, 21)

        def hier(w):
            return hierarchical_reduce_scatter_sum(w[0], "d", spec,
                                                   chunks=chunks)

        def flat(w):
            return decomposed_reduce_scatter_sum(w[0], "d",
                                                 chunks=chunks)

        def native(w):
            return jax.lax.psum_scatter(w[0], "d",
                                        scatter_dimension=0, tiled=True)

        a = np.asarray(_shm(mesh, hier, (P("d"),), P("d"))(wide))
        b = np.asarray(_shm(mesh, native, (P("d"),), P("d"))(wide))
        c = np.asarray(_shm(mesh, flat, (P("d"),), P("d"))(wide))
        np.testing.assert_array_equal(
            a.astype(np.float32), b.astype(np.float32))
        np.testing.assert_array_equal(
            a.astype(np.float32), c.astype(np.float32))

    @pytest.mark.parametrize("shape", MESHES, ids=str)
    @pytest.mark.parametrize("dtype", (jnp.float32, jnp.bfloat16),
                             ids=lambda d: d.__name__)
    def test_hier_all_to_all_bitwise(self, eight_devices, shape, dtype):
        from hcache_deepspeed_tpu.comm.hierarchical import (
            hierarchical_all_to_all_rows, make_mesh_spec)
        mesh = _mesh(8)
        spec = make_mesh_spec(shape)
        rows = _payload(64, 11, dtype, seed=6).reshape(8, 8, 11)

        def hier(r):
            return hierarchical_all_to_all_rows(r[0], "d", spec)[None]

        def native(r):
            return jax.lax.all_to_all(r[0], "d", 0, 0)[None]

        a = np.asarray(_shm(mesh, hier, (P("d"),), P("d"))(rows))
        b = np.asarray(_shm(mesh, native, (P("d"),), P("d"))(rows))
        np.testing.assert_array_equal(
            a.astype(np.float32), b.astype(np.float32))

    @pytest.mark.parametrize("groups", (
        [[0, 1, 2, 3], [4, 5, 6, 7]],
        [[0, 4], [1, 5], [2, 6], [3, 7]],   # strided (long-haul lines)
    ), ids=("contiguous", "strided"))
    @pytest.mark.parametrize("chunks", CHUNKS)
    def test_grouped_all_to_all_rows_bitwise(self, eight_devices,
                                             groups, chunks):
        """The grouped primitive underneath every hierarchical phase:
        bitwise vs the native grouped all_to_all, contiguous AND
        strided groups, uneven chunks included."""
        mesh = _mesh(8)
        m = len(groups[0])
        rows = _payload(8 * m, 13, jnp.float32, seed=7).reshape(8, m, 13)

        def ring(r):
            return decomposed_all_to_all_rows(
                r[0], "d", axis_index_groups=groups, chunks=chunks)[None]

        def native(r):
            return jax.lax.all_to_all(r[0], "d", 0, 0,
                                      axis_index_groups=groups)[None]

        a = np.asarray(_shm(mesh, ring, (P("d"),), P("d"))(rows))
        b = np.asarray(_shm(mesh, native, (P("d"),), P("d"))(rows))
        np.testing.assert_array_equal(a, b)

    def test_grouped_reduce_scatter_bitwise(self, eight_devices):
        mesh = _mesh(8)
        groups = [[0, 2, 4, 6], [1, 3, 5, 7]]
        x = _payload(8, 4 * 9, jnp.float32, seed=8).reshape(8, 4, 9)

        def ring(xl):
            return decomposed_reduce_scatter_sum(
                xl[0], "d", axis_index_groups=groups)

        def native(xl):
            return jax.lax.psum_scatter(
                xl[0], "d", scatter_dimension=0, tiled=True,
                axis_index_groups=groups)

        a = np.asarray(_shm(mesh, ring, (P("d"),), P("d"))(x))
        b = np.asarray(_shm(mesh, native, (P("d"),), P("d"))(x))
        np.testing.assert_array_equal(a, b)


class TestPermuteByteAttribution:
    """Ring-chunk sends must land in the comms accounting with the
    ``collective_permute`` op kind — not silently unattributed."""

    def test_ring_bytes_logged_with_kind(self, eight_devices):
        mesh = _mesh(8)
        logger = get_comms_logger()
        logger.configure(enabled=True)
        logger.reset()
        x = _payload(8, 40, jnp.float32)

        def ring(xl):
            return ring_all_gather(xl[0], "d",
                                   op_name="test_ring_ag")[None]

        # logging happens at TRACE time
        _shm(mesh, ring, (P("d"),), P("d"))(x)
        summary = logger.permute_bytes_summary()
        assert logger.op_kinds.get("test_ring_ag") == "collective_permute"
        # 7 neighbor steps x 40 fp32 elements per device trace
        assert summary.get("test_ring_ag") == 7 * 40 * 4, summary
        logger.reset()
        logger.configure(enabled=False)
