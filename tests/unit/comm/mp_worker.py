"""Two-process data-parallel worker for the multi-host rendezvous test.

Run by ``test_multiprocess.py`` through the per-host launcher
(``launcher/launch.py``) with torchrun-style env (RANK / WORLD_SIZE /
MASTER_ADDR / MASTER_PORT), the same path a real multi-host deployment
takes (reference: ``deepspeed/launcher/launch.py`` spawning ranks that
each call ``deepspeed.init_distributed``). Each process owns ONE cpu
device, so the two processes form a genuine 2-device ``data`` mesh with
cross-process collectives riding gloo — the CI stand-in for DCN.

``HDS_TEST_ZERO_STAGE`` (default 0) picks the ZeRO stage — stage 3
shards every parameter across the process boundary, so the per-layer
weight gathers themselves ride the cross-process transport.

Prints one line per step: ``LOSS <rank> <step> <loss>``.
"""

import os
import sys

import numpy as np


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    import hcache_deepspeed_tpu as hds
    from hcache_deepspeed_tpu.comm import comm
    from hcache_deepspeed_tpu.models.gpt2 import GPT2LMHeadModel, gpt2_tiny
    from hcache_deepspeed_tpu.parallel import topology as topo_mod

    comm.init_distributed()   # HDS_* env, normalized by launcher.launch
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 2, jax.device_count()
    rank = jax.process_index()

    topo = topo_mod.initialize_topology(topo_mod.TopologySpec(data=2))
    mcfg = gpt2_tiny()
    model = GPT2LMHeadModel(mcfg)

    # global batch 4 = 2 rows per process; every leaf handed to
    # train_batch is the PROCESS-LOCAL shard (the engine rebuilds the
    # global array via make_array_from_process_local_data)
    rng = np.random.default_rng(7)
    global_batches = [rng.integers(0, mcfg.vocab_size, (4, 16),
                                   dtype=np.int32) for _ in range(3)]
    engine, _, _, _ = hds.initialize(
        model=model, topology=topo,
        example_batch={"input_ids": global_batches[0][2 * rank:2 * rank + 2]},
        config={
            "train_batch_size": 4,
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {
                "stage": int(os.environ.get("HDS_TEST_ZERO_STAGE", "0")),
                "min_shard_size": 1,
            },
            "steps_per_print": 10 ** 9,
        })
    for step, gb in enumerate(global_batches):
        local = gb[2 * rank:2 * rank + 2]
        loss = float(engine.train_batch(batch={"input_ids": local}))
        print(f"LOSS {rank} {step} {loss:.8f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
