"""16-device hierarchical factorings (ISSUE 15): the grouped-ring
machinery past the 8-device toy matrix — 4x4 and 2x8 meshes on a
16-virtual-device CPU child process (the conftest pins the parent at 8
devices, so the child re-launches with its own
``--xla_force_host_platform_device_count=16``). Slow tier: one child
interpreter + several 16-way compiles.

The child program lives in ``comm/benchmark.py`` (SIXTEEN_DEV_CHILD /
``run_16dev_parity``) and is shared with ``bench.py --zero-overlap``'s
hier-16dev phase, so the committed artifact and this test exercise the
same program. Gates: hierarchical all-gather / reduce-scatter /
all-to-all bitwise vs native at both factorings (fp32 + bf16), the
unified hpZ tier at hpz=4 on 4x4 bitwise vs the native grouped
gather, and phase-pipelined parity at pipeline_chunks=2. The 256 =
16x16 factoring is covered at spec level (no arrays) in
test_hierarchical.py ``TestPodScaleSpecBookkeeping``.
"""

import os

import pytest

from hcache_deepspeed_tpu.comm.benchmark import run_16dev_parity

pytestmark = pytest.mark.slow

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                     "..", "..", ".."))


class TestHierarchical16Devices:

    def test_4x4_and_2x8_parity(self):
        facts = run_16dev_parity(repo_root=_REPO)
        assert facts["parity"], facts
        meshes = {tuple(s["mesh"]) for s in facts["shapes"]}
        assert meshes == {(4, 4), (2, 8)}
        dtypes = {s["dtype"] for s in facts["shapes"]}
        assert dtypes == {"float32", "bfloat16"}
        for s in facts["shapes"]:
            assert all(s["bitwise"].values()), s
        assert facts["hpz_tier_bitwise"], facts
