"""Quantized/compressed collective tests.

Reference analog: ``tests/unit/comm/test_coalesced_collectives.py`` (qgZ
reduce) + ``tests/unit/runtime/comm/`` compressed backend tests.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hcache_deepspeed_tpu.comm.quantized import (all_to_all_quant_reduce,
                                                 compressed_allreduce,
                                                 quantized_all_gather)
from hcache_deepspeed_tpu.parallel import topology as topo_mod


@pytest.fixture
def data8(eight_devices):
    return topo_mod.initialize_topology(topo_mod.TopologySpec(data=8))


class TestQuantizedCollectives:

    def test_quantized_all_gather(self, data8):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((16, 64)).astype(np.float32)
        from jax.sharding import NamedSharding, PartitionSpec as P
        xs = jax.device_put(x, NamedSharding(data8.mesh, P("data")))
        out = jax.jit(lambda a: quantized_all_gather(
            a, topology=data8))(xs)
        assert out.shape == x.shape
        # int8 groupwise quantization: ~1% relative error budget
        err = np.abs(np.asarray(out) - x).max() / np.abs(x).max()
        assert err < 0.02

    def test_all_to_all_quant_reduce(self, data8):
        rng = np.random.default_rng(1)
        # per-device distinct gradients: [8, T, D], device i holds row i
        per_dev = rng.standard_normal((8, 16, 32)).astype(np.float32)
        from jax.sharding import NamedSharding, PartitionSpec as P
        stacked = jax.device_put(per_dev,
                                 NamedSharding(data8.mesh, P("data")))
        out = jax.jit(lambda s: all_to_all_quant_reduce(
            s, topology=data8))(stacked)
        mean = per_dev.mean(axis=0)          # [16, 32]
        got = np.asarray(out)
        rel = np.abs(got - mean).max() / (np.abs(mean).max() + 1e-9)
        assert rel < 0.05

    def test_compressed_allreduce_error_feedback(self, data8):
        rng = np.random.default_rng(2)
        x = rng.standard_normal((64,)).astype(np.float32)
        err0 = np.zeros_like(x)
        avg, new_err = jax.jit(lambda a, e: compressed_allreduce(
            a, e, topology=data8))(x, err0)
        # all devices hold identical x: avg = sign(x) * mean|x|
        expect = np.sign(x) * np.abs(x).mean()
        np.testing.assert_allclose(np.asarray(avg), expect, atol=1e-5)
        # error feedback carries exactly the compression residual
        np.testing.assert_allclose(np.asarray(new_err), x - expect,
                                   atol=1e-5)


class TestOnebitAdam:

    def test_converges_and_compresses(self, data8):
        """Distributed quadratic fit: warmup then 1-bit stage must keep
        converging (reference: onebit adam convergence tests)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from hcache_deepspeed_tpu.runtime.onebit import onebit_adam

        target = np.linspace(-1, 1, 32).astype(np.float32)
        init, update = onebit_adam(lr=5e-2, freeze_step=10)

        params = {"w": jnp.zeros(32, jnp.float32)}
        state = init(params)

        # per-device data shard: quadratic loss on its slice of a batch
        rng = np.random.default_rng(0)
        noise = rng.standard_normal((8, 32)).astype(np.float32) * 0.05

        # per-device worker error is axis-stacked at the jit level
        # (see onebit.py docstring): [8, ...] sharded on data
        state = state._replace(error=jax.tree.map(
            lambda e: jnp.zeros((8,) + e.shape, e.dtype), state.error))
        state_specs = state._replace(
            m=jax.tree.map(lambda _: P(), state.m),
            v=jax.tree.map(lambda _: P(), state.v),
            error=jax.tree.map(lambda _: P("data"), state.error),
            step=P())

        def make_step(compressed):
            @functools.partial(
                jax.shard_map, mesh=data8.mesh, axis_names={"data"},
                in_specs=(P(), state_specs, P("data")),
                out_specs=(P(), state_specs),
                check_vma=False)
            def train_step(params, state, local_noise):
                tgt = jnp.asarray(target) + local_noise[0]
                grads = {"w": params["w"] - tgt}  # local grad, unreduced
                local = state._replace(
                    error=jax.tree.map(lambda e: e[0], state.error))
                updates, new = update(grads, local, params,
                                      compressed=compressed)
                new = new._replace(
                    error=jax.tree.map(lambda e: e[None], new.error))
                params = jax.tree.map(lambda p, u: p + u, params, updates)
                return params, new

            return jax.jit(train_step)

        warm_step, comp_step = make_step(False), make_step(True)
        noise_sharded = jax.device_put(
            noise, NamedSharding(data8.mesh, P("data")))

        def loss(p):
            return float(jnp.mean((p["w"] - target) ** 2))

        # block every iteration: unsynchronized launches of
        # collective-bearing programs deadlock XLA's CPU rendezvous
        # (see tests/conftest.py harness rule)
        l0 = loss(params)
        for _ in range(15):          # warmup stage
            params, state = warm_step(params, state, noise_sharded)
            jax.block_until_ready(params)
        l_warm = loss(params)
        for _ in range(60):          # compression stage
            params, state = comp_step(params, state, noise_sharded)
            jax.block_until_ready(params)
        l_final = loss(params)
        assert int(jax.device_get(jax.tree.leaves(state.step)[0])) == 75
        assert l_warm < l0
        assert l_final < l_warm / 4
        # momentum stays synchronized across devices in the 1-bit stage
        m = state.m["w"]
        assert np.allclose(*[np.asarray(s.data) for s in
                             list(m.addressable_shards)[:2]])


class TestOnebitLambAndZeroOneAdam:
    """OnebitLamb + ZeroOneAdam (reference: fp16/onebit/{lamb,zoadam}.py)
    on the same shard_map harness as TestOnebitAdam."""

    def _harness(self, data8, init, make_update, steps_plan, state_spec_fn,
                 init_scale=0.0):
        from jax.sharding import NamedSharding, PartitionSpec as P
        rng = np.random.default_rng(0)
        target = rng.standard_normal((64,)).astype(np.float32)
        # LAMB steps scale with ||p||, so its test starts off-zero
        params = {"w": jnp.asarray(init_scale * target +
                                   0.01 * rng.standard_normal(64),
                                   jnp.float32)}
        state = init(params)
        noise = 0.05 * rng.standard_normal((8, 1)).astype(np.float32)
        state_specs = state_spec_fn(state)

        step_cache = {}

        def get_step(flags):
            if flags not in step_cache:
                @functools.partial(
                    jax.shard_map, mesh=data8.mesh, axis_names={"data"},
                    in_specs=(P(), state_specs, P("data")),
                    out_specs=(P(), state_specs),
                    check_vma=False)
                def train_step(params, state, local_noise):
                    tgt = jnp.asarray(target) + local_noise[0]
                    grads = {"w": params["w"] - tgt}
                    local = state._replace(
                        error=jax.tree.map(lambda e: e[0], state.error))
                    updates, new = make_update(grads, local, params, flags)
                    new = new._replace(
                        error=jax.tree.map(lambda e: e[None], new.error))
                    params = jax.tree.map(lambda p, u: p + u, params,
                                          updates)
                    return params, new

                step_cache[flags] = jax.jit(train_step)
            return step_cache[flags]

        noise_sharded = jax.device_put(
            noise, NamedSharding(data8.mesh, P("data")))

        def loss(p):
            return float(jnp.mean((p["w"] - target) ** 2))

        losses = [loss(params)]
        for flags, n in steps_plan:
            step_fn = get_step(flags)
            for _ in range(n):
                # block each launch: see the conftest harness rule
                params, state = step_fn(params, state, noise_sharded)
                jax.block_until_ready(params)
            losses.append(loss(params))
        return losses, state

    def test_onebit_lamb_converges(self, data8):
        from hcache_deepspeed_tpu.runtime.onebit import onebit_lamb
        from jax.sharding import PartitionSpec as P
        init, update = onebit_lamb(lr=0.05, freeze_step=15)

        def spec_fn(state):
            return state._replace(
                m=jax.tree.map(lambda _: P(), state.m),
                v=jax.tree.map(lambda _: P(), state.v),
                error=jax.tree.map(lambda _: P("data"), state.error),
                coeff=jax.tree.map(lambda _: P(), state.coeff),
                step=P())

        losses, state = self._harness(
            data8, init,
            lambda g, s, p, compressed: update(g, s, p,
                                               compressed=compressed),
            [(False, 15), (True, 45)], spec_fn, init_scale=0.5)
        assert losses[1] < losses[0] / 10     # warmup converges
        # compressed stage keeps improving toward the per-device noise
        # floor (~0.0025 for the 0.05-sigma target jitter)
        assert losses[2] < losses[1] * 0.75
        # frozen trust coefficient is finite and positive
        c = float(jax.device_get(state.coeff["w"]))
        assert 0.01 <= c <= 10.0

    def test_zero_one_adam_converges(self, data8):
        """Local steps desynchronize m/v AND params across devices, so
        everything per-device is carried axis-stacked ([n, ...] on
        'data') — a replicated out_spec for varying values is undefined
        behavior (see onebit.py docstring)."""
        import functools
        from jax.sharding import NamedSharding, PartitionSpec as P
        from hcache_deepspeed_tpu.runtime.onebit import zero_one_adam
        init, update, sync_interval, is_sync = zero_one_adam(
            lr=0.05, var_freeze_step=20, local_step_scaler=20,
            local_step_clipper=3)
        assert sync_interval(0) == 1 and sync_interval(25) == 2
        assert sync_interval(10 ** 6) == 8  # clipper cap
        assert is_sync(0) and not is_sync(21)

        rng = np.random.default_rng(0)
        target = rng.standard_normal((64,)).astype(np.float32)
        params = {"w": jnp.zeros((8, 64), jnp.float32)}   # stacked
        state0 = init({"w": jnp.zeros((64,), jnp.float32)})
        state = state0._replace(
            m=jax.tree.map(lambda m: jnp.tile(m, (8, 1)), state0.m),
            v=jax.tree.map(lambda v: jnp.tile(v, (8, 1)), state0.v),
            error=jax.tree.map(lambda e: jnp.tile(e, (8, 1)),
                               state0.error))
        state_specs = state._replace(
            m=jax.tree.map(lambda _: P("data"), state.m),
            v=jax.tree.map(lambda _: P("data"), state.v),
            error=jax.tree.map(lambda _: P("data"), state.error),
            step=P())
        noise = 0.05 * rng.standard_normal((8, 1)).astype(np.float32)
        noise_sharded = jax.device_put(
            noise, NamedSharding(data8.mesh, P("data")))

        step_cache = {}

        def get_step(flags):
            if flags not in step_cache:
                sync, update_var = flags

                @functools.partial(
                    jax.shard_map, mesh=data8.mesh, axis_names={"data"},
                    in_specs=(P("data"), state_specs, P("data")),
                    out_specs=(P("data"), state_specs),
                    check_vma=False)
                def train_step(params, state, local_noise):
                    p = {"w": params["w"][0]}
                    tgt = jnp.asarray(target) + local_noise[0]
                    grads = {"w": p["w"] - tgt}
                    local = state._replace(
                        m=jax.tree.map(lambda m: m[0], state.m),
                        v=jax.tree.map(lambda v: v[0], state.v),
                        error=jax.tree.map(lambda e: e[0], state.error))
                    u, new = update(grads, local, p, sync=sync,
                                    update_var=update_var)
                    new = new._replace(
                        m=jax.tree.map(lambda m: m[None], new.m),
                        v=jax.tree.map(lambda v: v[None], new.v),
                        error=jax.tree.map(lambda e: e[None], new.error))
                    p = jax.tree.map(lambda a, b: (a + b)[None], p, u)
                    return p, new

                step_cache[flags] = jax.jit(train_step)
            return step_cache[flags]

        def loss(p):
            # mean loss across per-device replicas
            w = np.asarray(p["w"])
            return float(np.mean((w - target[None]) ** 2))

        losses = [loss(params)]
        for flags, n in [((True, True), 20),    # full sync + var update
                         ((True, False), 20),   # var frozen
                         ((False, False), 4),   # local steps
                         ((True, False), 16)]:
            step_fn = get_step(flags)
            for _ in range(n):
                params, state = step_fn(params, state, noise_sharded)
                jax.block_until_ready(params)
            losses.append(loss(params))
        assert losses[1] < losses[0]
        assert losses[-1] < losses[1]
