"""Wire-byte attribution for the quantized collectives.

Every quantized wire site must report a MATCHED pair through the comms
logger — the actual (int8 + scales) bytes under its op name and the
full-width bytes the same collective would have carried under
``<op>_unquantized_equiv`` — using the leaf's ACTUAL dtype for the
equivalent (the qwZ site used to hard-code bf16, under-reporting fp32
runs 2x). Covered sites: qwZ bucketed/per-leaf gathers, qgZ per-leaf
all-to-all, the bucketed quantized reduce-scatter
(``runtime/zero/qwire.py``), and Domino's opt-in int8 all-reduce.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from hcache_deepspeed_tpu.comm.comms_logging import get_comms_logger
from hcache_deepspeed_tpu.parallel.topology import DATA_AXIS


@pytest.fixture
def comms():
    logger = get_comms_logger()
    logger.configure(enabled=True)
    logger.reset()
    yield logger
    logger.reset()
    logger.configure(enabled=False)


def _mesh():
    return Mesh(np.array(jax.devices()[:8]).reshape(8), (DATA_AXIS,))


def _shmap(fn, in_specs, out_specs):
    return jax.jit(functools.partial(
        jax.shard_map, mesh=_mesh(), axis_names={DATA_AXIS},
        in_specs=in_specs, out_specs=out_specs, check_vma=False)(fn))


def _pair(comms, op):
    """(wire_bytes, unquantized_equiv_bytes) recorded for ``op``."""
    summary = comms.wire_savings_summary()
    assert op in summary, (op, sorted(summary))
    rec = summary[op]
    return rec["wire_bytes"], rec["unquantized_equiv_bytes"]


class TestWireByteAttribution:

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_qwz_gather_pair_uses_actual_dtype(self, eight_devices,
                                               comms, dtype):
        from hcache_deepspeed_tpu.runtime.zero.zeropp import \
            make_leaf_gather
        x = jnp.arange(8 * 16 * 4, dtype=dtype).reshape(8 * 16, 4)

        def gather(x_local):
            return make_leaf_gather(qw=True, hpz=1, group_size=64)(
                x_local, None, 0)

        out = _shmap(gather, (P(DATA_AXIS),), P())(x)
        assert out.shape == x.shape
        wire, equiv = _pair(comms, "qwZ_all_gather")
        # the unquantized equivalent is the per-device shard in the
        # leaf's ACTUAL dtype (8 devices trace as one program)
        shard_elems = x.size // 8
        assert equiv == shard_elems * jnp.dtype(dtype).itemsize
        assert wire < equiv

    def test_qgz_all_to_all_pair(self, eight_devices, comms):
        from hcache_deepspeed_tpu.runtime.zero.zeropp import \
            _quant_reduce_mean_dim
        g = jnp.arange(8 * 32, dtype=jnp.float32).reshape(8 * 32)

        def reduce(g_full):
            return _quant_reduce_mean_dim(g_full, 0, group_size=64)

        # cotangent enters FULL per device (the VJP layout)
        out = _shmap(reduce, (P(),), P(DATA_AXIS))(g)
        assert out.shape == g.shape
        wire, equiv = _pair(comms, "qgZ_all_to_all")
        assert equiv == g.size * 4
        assert wire < equiv

    @pytest.mark.parametrize("bits,max_frac", [(8, 0.30), (4, 0.17)])
    def test_qrs_bucketed_pair_and_fraction(self, eight_devices, comms,
                                            bits, max_frac):
        from hcache_deepspeed_tpu.runtime.zero.qwire import (
            QRS_OP, quantized_bucket_reduce_scatter_mean)
        leaves = [jnp.ones((8 * 256,), jnp.float32),
                  jnp.ones((8 * 128, 2), jnp.float32)]
        dims = [0, 0]

        def reduce(a, b):
            out, _ = quantized_bucket_reduce_scatter_mean(
                [a, b], dims, bucket_elements=10 ** 9, group_size=2048,
                bits=bits, error_feedback=False)
            return tuple(out)

        out = _shmap(reduce, (P(), P()),
                     (P(DATA_AXIS), P(DATA_AXIS)))(*leaves)
        assert out[0].shape == leaves[0].shape
        wire, equiv = _pair(comms, QRS_OP)
        total = sum(x.size for x in leaves)
        assert equiv == total * 4
        assert wire / equiv <= max_frac, (wire, equiv)

    def test_domino_int8_allreduce_pair(self, eight_devices, comms):
        from hcache_deepspeed_tpu.comm.quantized import \
            quantized_allreduce_body
        x = jnp.ones((16, 64), jnp.float32)

        def ar(x_local):
            y, e = quantized_allreduce_body(x_local, jnp.zeros_like(
                x_local), DATA_AXIS, group_size=128)
            return y, e

        y, _ = _shmap(ar, (P(),), (P(), P()))(x)
        np.testing.assert_allclose(np.asarray(y), 8 * np.ones((16, 64)),
                                   rtol=1e-2)
        wire, equiv = _pair(comms, "domino_half_allreduce_int8")
        # both legs (reduce-scatter + gather) counted full-width
        assert equiv == 2 * x.size * 4
        assert wire < equiv


class TestDecomposedTransportAttribution:
    """The ring transport must keep the quantized matched pairs intact
    (quantization logs before the transport choice) AND attribute its
    per-chunk permute sends under the ``collective_permute`` op kind —
    ring bytes never go missing from the accounting."""

    def test_qrs_decomposed_keeps_pair_and_logs_permutes(
            self, eight_devices, comms):
        from hcache_deepspeed_tpu.runtime.zero.qwire import (
            QRS_OP, quantized_bucket_reduce_scatter_mean)
        leaf = jnp.ones((8 * 256,), jnp.float32)

        def reduce(a):
            out, _ = quantized_bucket_reduce_scatter_mean(
                [a], [0], bucket_elements=10 ** 9, group_size=2048,
                error_feedback=False, collective_impl="decomposed")
            return out[0]

        _shmap(reduce, (P(),), P(DATA_AXIS))(leaf)
        # the quantized matched pair survives the transport swap
        wire, equiv = _pair(comms, QRS_OP)
        assert equiv == leaf.size * 4
        assert wire < equiv
        # and the ring chunks are attributed with their kind
        permutes = comms.permute_bytes_summary()
        assert "zero_ring_qrs" in permutes, permutes
        assert permutes["zero_ring_qrs"] > 0
        assert comms.op_kinds["zero_ring_qrs"] == "collective_permute"
        rec = comms.wire_savings_summary()[QRS_OP]
        assert rec["op_kind"] == "collective"

    def test_domino_decomposed_int8_same_totals(self, eight_devices,
                                                comms):
        """Transport swap must not change the quantized pair totals —
        same rows quantized, same bytes claimed."""
        from hcache_deepspeed_tpu.comm.quantized import \
            quantized_allreduce_body
        x = jnp.ones((16, 64), jnp.float32)

        def ar(impl):
            def f(x_local):
                return quantized_allreduce_body(
                    x_local, jnp.zeros_like(x_local), DATA_AXIS,
                    group_size=128, collective_impl=impl)
            return f

        _shmap(ar("native"), (P(),), (P(), P()))(x)
        native_pair = _pair(comms, "domino_half_allreduce_int8")
        comms.reset()
        _shmap(ar("decomposed"), (P(),), (P(), P()))(x)
        dec_pair = _pair(comms, "domino_half_allreduce_int8")
        assert native_pair == dec_pair
        assert comms.permute_bytes_summary().get(
            "domino_ring_allreduce_int8", 0) > 0


class TestFusedPermuteReconciliation:
    """ISSUE 18 satellite gate: the fused computation-collective
    kernels log their in-kernel ring steps as ``op_kind =
    "fused_permute"`` rows — and those rows must reconcile BYTE-EXACTLY
    with what the unfused transport of the same payload logs as
    ``collective_permute`` rows. Fusing the permute into the kernel
    never makes wire volume silent, and never double-counts it: the
    default lumped summary excludes fused rows, the widened-``kinds``
    summary and ``total_axis_bytes`` include them exactly once."""

    def _shards(self):
        from hcache_deepspeed_tpu.ops.quantized_matmul import \
            quantize_for_matmul
        rng = np.random.default_rng(18)
        w = jnp.asarray(rng.normal(size=(64, 16)), jnp.float32)
        q, s = quantize_for_matmul(w, 8)          # q [64,16], s [8,16]
        x = jnp.asarray(rng.normal(size=(4, 64)), jnp.float32)
        return x, q, s

    def test_fused_gather_rows_reconcile_with_unfused_ring(
            self, eight_devices, comms):
        from hcache_deepspeed_tpu.comm.ring import ring_all_gather
        from hcache_deepspeed_tpu.ops.fused_collective_matmul import (
            FUSED_GATHER_MM_OP, reference_fused_gather_matmul)
        x, q, s = self._shards()

        def fused(q_sh, s_sh):
            return reference_fused_gather_matmul(
                x, q_sh, s_sh, group_k=8, axis_name=DATA_AXIS,
                shard_dim=0)

        _shmap(fused, (P(DATA_AXIS), P(DATA_AXIS)), P())(q, s)
        fused_rows = comms.fused_bytes_summary()
        assert FUSED_GATHER_MM_OP in fused_rows, sorted(fused_rows)
        assert comms.op_kinds[FUSED_GATHER_MM_OP] == "fused_permute"
        # fused rows are NOT in the default (collective_permute-only)
        # lumped summary, ARE in the widened-kinds summary, exactly once
        assert FUSED_GATHER_MM_OP not in comms.permute_bytes_summary()
        widened = comms.permute_bytes_summary(
            kinds=("collective_permute", "fused_permute"))
        assert widened[FUSED_GATHER_MM_OP] == \
            fused_rows[FUSED_GATHER_MM_OP]
        # ...and they land in the wire-cost aggregate under the ring's
        # axis label
        assert comms.total_axis_bytes().get(DATA_AXIS, 0) >= \
            fused_rows[FUSED_GATHER_MM_OP]

        # unfused transport of the SAME payload: the plain ring gather
        # the bucketed pipeline would run — byte-exact reconciliation
        comms.reset()

        def unfused(q_sh, s_sh):
            wq = ring_all_gather(q_sh.reshape(-1), DATA_AXIS,
                                 op_name="unfused_gather")
            ws = ring_all_gather(s_sh.reshape(-1), DATA_AXIS,
                                 op_name="unfused_gather")
            return wq, ws

        _shmap(unfused, (P(DATA_AXIS), P(DATA_AXIS)),
               (P(DATA_AXIS), P(DATA_AXIS)))(q, s)
        unfused_rows = comms.permute_bytes_summary()
        assert unfused_rows["unfused_gather"] == \
            fused_rows[FUSED_GATHER_MM_OP], (unfused_rows, fused_rows)

    def test_streamed_schedule_same_wire_bytes(self, eight_devices,
                                               comms):
        """The in-flight lane (streamed schedule) moves the SAME bytes
        as the gather-then-matmul reference twin — overlap changes
        wall-clock, never wire volume."""
        from hcache_deepspeed_tpu.ops.fused_collective_matmul import (
            FUSED_GATHER_MM_OP, reference_fused_gather_matmul,
            streamed_fused_gather_matmul)
        x, q, s = self._shards()

        def run(fn):
            comms.reset()
            _shmap(lambda q_sh, s_sh: fn(
                x, q_sh, s_sh, group_k=8, axis_name=DATA_AXIS,
                shard_dim=0), (P(DATA_AXIS), P(DATA_AXIS)), P())(q, s)
            return comms.fused_bytes_summary()[FUSED_GATHER_MM_OP]

        assert run(reference_fused_gather_matmul) == \
            run(streamed_fused_gather_matmul)

    def test_fused_qrs_rows_reconcile_with_ring_a2a(
            self, eight_devices, comms):
        from hcache_deepspeed_tpu.comm.ring import \
            decomposed_all_to_all_rows
        from hcache_deepspeed_tpu.ops.fused_collective_matmul import (
            FUSED_QRS_OP, fused_qrs_exchange)
        rng = np.random.default_rng(7)
        pay = jnp.asarray(rng.integers(-127, 128, (8, 8, 6)), jnp.int8)
        sc = jnp.asarray(rng.normal(size=(8, 8, 2)), jnp.float32)

        def fused(p, s):
            return fused_qrs_exchange(p[0], s[0], axis_name=DATA_AXIS)

        _shmap(fused, (P(DATA_AXIS), P(DATA_AXIS)),
               (P(DATA_AXIS), P(DATA_AXIS)))(pay, sc)
        fused_rows = comms.fused_bytes_summary()
        assert FUSED_QRS_OP in fused_rows, sorted(fused_rows)
        assert comms.op_kinds[FUSED_QRS_OP] == "fused_permute"
        comms.reset()

        def unfused(p, s):
            pt = decomposed_all_to_all_rows(p[0], DATA_AXIS,
                                            op_name="unfused_a2a")
            st = decomposed_all_to_all_rows(s[0], DATA_AXIS,
                                            op_name="unfused_a2a")
            return pt, st

        _shmap(unfused, (P(DATA_AXIS), P(DATA_AXIS)),
               (P(DATA_AXIS), P(DATA_AXIS)))(pay, sc)
        unfused_rows = comms.permute_bytes_summary()
        assert unfused_rows["unfused_a2a"] == \
            fused_rows[FUSED_QRS_OP], (unfused_rows, fused_rows)


class TestInt4Pack:

    def test_roundtrip(self):
        from hcache_deepspeed_tpu.runtime.zero.qwire import (pack_int4,
                                                             unpack_int4)
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.integers(-8, 8, (4, 33)), jnp.int8)
        packed = pack_int4(q)
        assert packed.dtype == jnp.uint8
        assert packed.shape == (4, 17)
        back = unpack_int4(packed, 33)
        np.testing.assert_array_equal(np.asarray(back), np.asarray(q))


class TestHierSiteReconciliation:
    """ISSUE 15 satellite bugfix gate: with the hpZ secondary refresh
    and the bucketed/per-leaf gathers riding the mesh, the per-axis
    map (``permute_axis_bytes``) must still reconcile EXACTLY with the
    lumped ``permute_bytes_summary`` — every new mesh site attributes
    each byte exactly once (no double-count between the new
    ``zero_hier_secondary`` / ``zero_hier_leaf_gather`` ops and the
    bucketed lanes' ``zero_hier_all_gather``)."""

    def test_per_axis_reconciles_with_lumped_summary(
            self, eight_devices, comms):
        import jax.numpy as jnp

        from hcache_deepspeed_tpu.comm.hierarchical import \
            make_mesh_spec
        from hcache_deepspeed_tpu.runtime.zero.zeropp import (
            bucketed_all_gather, build_secondary, make_leaf_gather)
        spec = make_mesh_spec([2, 4])
        rng = np.random.default_rng(9)
        x = jnp.asarray(rng.normal(size=(64, 4)), jnp.float32)
        y = jnp.asarray(rng.normal(size=(32, 2)), jnp.float32)

        def f(a, b):
            sec = build_secondary(
                {"a": a, "b": b}, [0, 0], 4,
                collective_impl="hierarchical", mesh_spec=spec)
            lg = make_leaf_gather(qw=False, hpz=4, group_size=64,
                                  collective_impl="hierarchical",
                                  mesh_spec=spec)
            full_a = lg(a, sec[0], 0)
            out = bucketed_all_gather(
                [b], [sec[1]], [0], qw=False, hpz=4, group_size=64,
                bucket_elements=10 ** 9,
                collective_impl="hierarchical", mesh_spec=spec)
            return full_a, out[0]

        _shmap(f, (P(DATA_AXIS), P(DATA_AXIS)), (P(), P()))(x, y)
        lumped = comms.permute_bytes_summary()
        per_axis = comms.permute_axis_bytes()
        # all three mesh sites present...
        assert {"zero_hier_secondary", "zero_hier_leaf_gather",
                "zero_hier_all_gather"} <= set(lumped), sorted(lumped)
        # ...and every op's per-axis map sums exactly to its lumped
        # total — byte-exact reconciliation, no double-count
        for op, total in lumped.items():
            assert sum(per_axis[op].values()) == total, (op, per_axis)
        # the secondary refresh crosses the mesh (both axes); the
        # hpZ-tier gathers stay intra-only
        assert set(per_axis["zero_hier_secondary"]) == {"intra",
                                                        "inter"}
        assert set(per_axis["zero_hier_leaf_gather"]) == {"intra"}
        assert set(per_axis["zero_hier_all_gather"]) == {"intra"}
