import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec

from hcache_deepspeed_tpu.parallel.topology import (MeshTopology,
                                                    TopologySpec,
                                                    get_topology,
                                                    initialize_topology)
from hcache_deepspeed_tpu.runtime.zero.sharding import (ZeroShardingPolicy,
                                                        choose_shard_spec)


class TestTopology:
    def test_default_all_data(self):
        topo = MeshTopology()
        assert topo.data_size == len(jax.devices())
        assert topo.world_size == len(jax.devices())
        assert topo.batch_shard_axes() == ("data",)

    def test_resolve_spec(self):
        spec = TopologySpec(pipe=2, tensor=2).resolve(8)
        assert spec.data == 2

    def test_bad_spec(self):
        with pytest.raises(ValueError):
            TopologySpec(pipe=3).resolve(8)

    def test_grad_reduce_axes(self):
        topo = MeshTopology(TopologySpec(pipe=1, data=2, expert=2, seq=2,
                                         tensor=1))
        assert topo.grad_reduce_axes() == ("data", "expert", "seq")
        assert topo.grad_reduce_axes(expert_param=True) == ("data", "seq")
        assert topo.dp_world_size() == 4

    def test_singleton(self):
        t1 = initialize_topology(TopologySpec(data=4, tensor=2))
        assert get_topology() is t1
        assert t1.tensor_size == 2


class TestZeroSharding:
    def _topo(self):
        return MeshTopology(TopologySpec(data=8))

    def test_choose_spec_picks_divisible_dim(self):
        topo = self._topo()
        spec = choose_shard_spec((6, 128, 512), topo, ("data",), min_size=1)
        assert spec == PartitionSpec(None, None, "data")

    def test_choose_spec_small_stays_replicated(self):
        topo = self._topo()
        spec = choose_shard_spec((4, 4), topo, ("data",), min_size=2 ** 14)
        assert spec == PartitionSpec(None, None)

    def test_choose_spec_respects_base(self):
        topo = MeshTopology(TopologySpec(data=4, tensor=2))
        base = PartitionSpec(None, "tensor")
        spec = choose_shard_spec((1024, 512), topo, ("data",), base, min_size=1)
        assert spec == PartitionSpec("data", "tensor")

    @pytest.mark.parametrize("stage,expect", [
        (0, (False, False, False)),
        (1, (False, False, True)),
        (2, (False, True, True)),
        (3, (True, True, True)),
    ])
    def test_stage_table(self, stage, expect):
        topo = self._topo()
        policy = ZeroShardingPolicy(stage, topo, min_shard_size=1)
        leaf = np.zeros((256, 64), np.float32)
        shard_param, shard_grad, shard_opt = expect
        is_sharded = lambda s: any(x is not None for x in tuple(s))
        assert is_sharded(policy.param_spec((), leaf)) == shard_param
        assert is_sharded(policy.grad_spec((), leaf)) == shard_grad
        assert is_sharded(policy.opt_spec((), leaf)) == shard_opt
