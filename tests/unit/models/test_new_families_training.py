"""OPT / Falcon / Phi under the full engine: ZeRO-3 on an 8-device mesh
with AutoTP-derived sharding — the new families must be first-class
*training* citizens, not serving-only (reference: any HF model trains
under deepspeed.initialize)."""

import numpy as np
import pytest

import hcache_deepspeed_tpu as hds
from hcache_deepspeed_tpu.parallel import topology as topo_mod


def _model(family):
    if family == "opt":
        from hcache_deepspeed_tpu.models.opt import (OPTForCausalLM,
                                                     opt_tiny)
        cfg = opt_tiny(use_flash=False)
        return OPTForCausalLM(cfg), cfg
    if family == "falcon":
        from hcache_deepspeed_tpu.models.falcon import (FalconForCausalLM,
                                                        falcon_tiny)
        cfg = falcon_tiny(use_flash=False)
        return FalconForCausalLM(cfg), cfg
    from hcache_deepspeed_tpu.models.phi import PhiForCausalLM, phi_tiny
    cfg = phi_tiny(use_flash=False)
    return PhiForCausalLM(cfg), cfg


@pytest.mark.parametrize("family", ["opt", "falcon", "phi"])
def test_zero3_training_loss_decreases(eight_devices, family):
    model, cfg = _model(family)
    topo = topo_mod.initialize_topology(topo_mod.TopologySpec(data=4,
                                                              tensor=2))
    try:
        rng = np.random.default_rng(0)
        batch = {"input_ids": rng.integers(0, cfg.vocab_size, (8, 32),
                                           dtype=np.int32)}
        engine, _, _, _ = hds.initialize(
            model=model, example_batch=batch, topology=topo,
            config={"train_batch_size": 8,
                    "train_micro_batch_size_per_gpu": 2,
                    "optimizer": {"type": "AdamW",
                                  "params": {"lr": 5e-3}},
                    "zero_optimization": {"stage": 3,
                                          "min_shard_size": 1}})
        losses = [float(engine.train_batch(batch=batch))
                  for _ in range(6)]
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]
    finally:
        topo_mod.reset_topology()


def test_see_memory_usage_runs():
    from hcache_deepspeed_tpu.utils.memory import see_memory_usage
    out = see_memory_usage("unit-test probe")
    assert "device_used_gb" in out and "host_rss_gb" in out
