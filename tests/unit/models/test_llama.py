"""Llama family tests (reference analog: tests/unit/model zoo usage —
SimpleModel-style train-and-converge checks, plus TP sharding validation)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import hcache_deepspeed_tpu as hds
from hcache_deepspeed_tpu.models.llama import (LlamaForCausalLM, llama_tiny,
                                               llama_tp_spec_fn)
from hcache_deepspeed_tpu.parallel import topology as topo_mod


def _batch(cfg, B=4, T=32, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, cfg.vocab_size, (B, T),
                                      dtype=np.int32)}


class TestLlamaModel:
    def test_forward_loss_finite(self):
        cfg = llama_tiny()
        model = LlamaForCausalLM(cfg)
        batch = _batch(cfg)
        params = model.init(jax.random.PRNGKey(0), batch, train=False)
        loss = model.apply(params, batch, train=False)
        assert np.isfinite(float(loss))
        # random init => loss near ln(vocab)
        assert abs(float(loss) - np.log(cfg.vocab_size)) < 1.0

    def test_gqa_heads(self):
        cfg = llama_tiny(n_head=4, n_kv_head=1)  # MQA
        model = LlamaForCausalLM(cfg)
        batch = _batch(cfg)
        params = model.init(jax.random.PRNGKey(0), batch, train=False)
        kv_kernel = params["params"]["layers_0"]["self_attn"]["k_proj"][
            "kernel"]
        assert kv_kernel.shape == (cfg.hidden_size,
                                   cfg.head_dim * cfg.n_kv_head)
        loss = model.apply(params, batch, train=False)
        assert np.isfinite(float(loss))

    def test_trains_loss_decreases(self):
        cfg = llama_tiny()
        model = LlamaForCausalLM(cfg)
        batch = _batch(cfg, B=8)
        config = {
            "train_batch_size": 8,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 0},
        }
        engine, _, _, _ = hds.initialize(model=model, config=config,
                                         example_batch=batch)
        losses = [float(engine.train_batch(batch=batch)) for _ in range(8)]
        assert losses[-1] < losses[0] - 0.5, losses

    def test_zero3_tp_mesh(self, eight_devices):
        topo = topo_mod.initialize_topology(
            topo_mod.TopologySpec(data=4, tensor=2))
        cfg = llama_tiny()
        model = LlamaForCausalLM(cfg)
        batch = _batch(cfg, B=8)
        config = {
            "train_batch_size": 8,
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 3, "min_shard_size": 1},
        }
        engine, _, _, _ = hds.initialize(model=model, config=config,
                                         example_batch=batch, topology=topo,
                                         tp_spec_fn=llama_tp_spec_fn)
        l0 = float(engine.train_batch(batch=batch))
        l1 = float(engine.train_batch(batch=batch))
        assert np.isfinite(l0) and np.isfinite(l1)

    def test_remat_matches(self):
        cfg_a = llama_tiny(remat=False)
        cfg_b = llama_tiny(remat=True)
        model_a = LlamaForCausalLM(cfg_a)
        model_b = LlamaForCausalLM(cfg_b)
        batch = _batch(cfg_a)
        params = model_a.init(jax.random.PRNGKey(0), batch, train=False)
        la = model_a.apply(params, batch, train=False)
        lb = model_b.apply(params, batch, train=False)
        np.testing.assert_allclose(float(la), float(lb), rtol=1e-5)
