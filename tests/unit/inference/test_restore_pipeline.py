"""Decode-interleaved HCache restore: the dual-lane restore pipeline.

The engine-side lane surface (``begin_restore`` / ``advance_restores``)
must (a) keep exact bookkeeping — tickets, chunk counts, in-flight
guards — and (b) be *invisible to results*: interleaving a restore's
replay chunks with resident decode dispatches yields bitwise-identical
logits to the sequential restore-then-decode path on the CPU backend
(interleaved dispatches only read OTHER sequences' blocks)."""

import jax
import numpy as np
import pytest

from hcache_deepspeed_tpu.inference import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig)
from hcache_deepspeed_tpu.models.llama import LlamaForCausalLM, llama_tiny


@pytest.fixture(scope="module")
def tiny_model():
    cfg = llama_tiny(max_positions=128, use_flash=False)
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": np.zeros((1, 8), np.int32)},
                        train=False)["params"]
    return cfg, params


def build_engine(cfg, params, chunk_layers=1):
    return InferenceEngineV2(
        cfg, params,
        config=RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 8,
                           "max_ragged_batch_size": 128,
                           "max_ragged_sequence_count": 4,
                           "max_context": 128},
            kv_cache={"block_size": 8, "num_blocks": 17,
                      "cache_dtype": "float32"},
            # one layer per chunk: the tiny model's 2 layers become 2
            # replay chunks, so the lane genuinely spans advances
            hcache={"enable_latents": True,
                    "restore_chunk_layers": chunk_layers}))


def _harvest(cfg, engine, rng):
    """Prefill a resident (uid 0) and a victim (uid 1); flush the
    victim keeping its latents — the standard preempt-to-latents
    setup. Returns (p0, p1, latents_1)."""
    p0 = list(map(int, rng.integers(0, cfg.vocab_size, 12)))
    p1 = list(map(int, rng.integers(0, cfg.vocab_size, 20)))
    _, lat = engine.put([0, 1], [p0, p1])
    engine.flush(1)
    return p0, p1, lat[1]


class TestLaneBookkeeping:
    def test_ticket_and_chunk_accounting(self, tiny_model):
        cfg, params = tiny_model
        eng = build_engine(cfg, params)
        rng = np.random.default_rng(0)
        _, p1, lat1 = _harvest(cfg, eng, rng)
        stats0 = dict(eng.restore_stats)
        ticket = eng.begin_restore([1], [p1], [lat1])
        assert not ticket.done and ticket.uids == [1]
        assert eng.restoring_uids == [1]
        assert eng.pending_restore_chunks == cfg.n_layer
        # restores/sequences count at begin; chunks as they issue
        assert eng.restore_stats["restores"] == stats0["restores"] + 1
        assert eng.restore_stats["chunks_issued"] == \
            stats0["chunks_issued"]
        chunks, completed, touched = eng.advance_restores(1)
        assert (chunks, completed, touched) == (1, [], [1])
        assert not ticket.done and eng.pending_restore_chunks == \
            cfg.n_layer - 1
        chunks, completed, touched = eng.advance_restores()
        assert chunks == cfg.n_layer - 1 and completed == [1]
        assert ticket.done and eng.restoring_uids == []
        assert eng.restore_stats["chunks_issued"] == \
            stats0["chunks_issued"] + cfg.n_layer
        # restored sequence is live and decodable
        assert eng.state.get_sequence(1).seen_tokens == len(p1)
        eng.flush(0)
        eng.flush(1)

    def test_open_lane_guards_put_and_flush(self, tiny_model):
        cfg, params = tiny_model
        eng = build_engine(cfg, params)
        rng = np.random.default_rng(1)
        _, p1, lat1 = _harvest(cfg, eng, rng)
        eng.begin_restore([1], [p1], [lat1])
        with pytest.raises(RuntimeError, match="open restore lane"):
            eng.put([1], [[3]])
        with pytest.raises(RuntimeError, match="open restore lane"):
            eng.flush(1)
        with pytest.raises(RuntimeError, match="open restore lane"):
            eng.begin_restore([1], [p1], [lat1])
        eng.advance_restores()
        eng.put([1], [[3]])          # lane drained: decodable again
        eng.flush(0)
        eng.flush(1)

    def test_restore_kv_drains_through_the_lane(self, tiny_model):
        """The synchronous API is the lane run to completion — no lane
        may remain open after it returns."""
        cfg, params = tiny_model
        eng = build_engine(cfg, params)
        rng = np.random.default_rng(2)
        _, p1, lat1 = _harvest(cfg, eng, rng)
        eng.restore_kv([1], [p1], [lat1])
        assert eng.pending_restore_chunks == 0
        assert eng.restoring_uids == []
        assert eng.state.get_sequence(1).seen_tokens == len(p1)
        eng.flush(0)
        eng.flush(1)


class TestInterleavedParity:
    def test_interleaved_restore_bitwise_matches_sequential(
            self, tiny_model):
        """The acceptance parity gate: restore chunks interleaved with
        a resident's decode steps produce logits identical to the
        sequential restore-then-decode path — for the resident AND the
        restored sequence."""
        cfg, params = tiny_model
        rng = np.random.default_rng(3)
        feed0 = [int(t) for t in rng.integers(0, cfg.vocab_size, 3)]
        feed1 = int(rng.integers(0, cfg.vocab_size))

        # path A: interleaved — one decode dispatch between every
        # replay chunk
        eng_a = build_engine(cfg, params)
        p0, p1, lat1 = _harvest(cfg, eng_a, rng)
        logits_a = []
        ticket = eng_a.begin_restore([1], [p1], [lat1])
        i = 0
        while not ticket.done:
            la, _ = eng_a.put([0], [[feed0[i]]])
            logits_a.append(np.asarray(la[0]))
            i += 1
            eng_a.advance_restores(1)
        # drain the remaining resident feeds + the restored sequence
        for t in feed0[i:]:
            la, _ = eng_a.put([0], [[t]])
            logits_a.append(np.asarray(la[0]))
        l1a, _ = eng_a.put([1], [[feed1]])

        # path B: sequential — full restore, then the same decodes
        # (fresh rng at the same point in the stream ⇒ same prompts)
        eng_b = build_engine(cfg, params)
        rng_b = np.random.default_rng(3)
        rng_b.integers(0, cfg.vocab_size, 3)
        rng_b.integers(0, cfg.vocab_size)
        p0b, p1b, lat1b = _harvest(cfg, eng_b, rng_b)
        assert p0b == p0 and p1b == p1
        eng_b.restore_kv([1], [p1b], [lat1b])
        logits_b = []
        for t in feed0:
            lb, _ = eng_b.put([0], [[t]])
            logits_b.append(np.asarray(lb[0]))
        l1b, _ = eng_b.put([1], [[feed1]])

        assert len(logits_a) == len(logits_b)
        for a, b in zip(logits_a, logits_b):
            np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(np.asarray(l1a[0]),
                                      np.asarray(l1b[0]))

    def test_interleaved_restore_multi_sequence_group(self, tiny_model):
        """A grouped (two-uid) lane restored chunk-by-chunk under
        decode traffic equals the one-shot grouped restore."""
        cfg, params = tiny_model
        rng = np.random.default_rng(4)
        p0 = list(map(int, rng.integers(0, cfg.vocab_size, 8)))
        pr = [list(map(int, rng.integers(0, cfg.vocab_size, n)))
              for n in (10, 14)]

        def harvest(eng):
            _, lat = eng.put([0, 1, 2], [p0] + pr)
            eng.flush(1)
            eng.flush(2)
            return lat

        eng_a = build_engine(cfg, params)
        lat = harvest(eng_a)
        ticket = eng_a.begin_restore([1, 2], pr, [lat[1], lat[2]])
        while not ticket.done:
            eng_a.put([0], [[5]])
            eng_a.advance_restores(1)
        l_a, _ = eng_a.put([1, 2], [[7], [9]])

        eng_b = build_engine(cfg, params)
        lat = harvest(eng_b)
        eng_b.restore_kv([1, 2], pr, [lat[1], lat[2]])
        eng_b.put([0], [[5]])
        eng_b.put([0], [[5]])
        l_b, _ = eng_b.put([1, 2], [[7], [9]])
        np.testing.assert_array_equal(np.asarray(l_a),
                                      np.asarray(l_b))


class TestResilienceHooks:
    """Real-engine fault sites + abort_restore (the resilience layer's
    engine surface; the scheduler-level recovery paths are covered on
    the sim in tests/unit/serving/)."""

    def test_abort_restore_frees_lane_state(self, tiny_model):
        cfg, params = tiny_model
        eng = build_engine(cfg, params)
        rng = np.random.default_rng(11)
        p0, p1, lat1 = _harvest(cfg, eng, rng)
        free_before_lane = eng.state.free_blocks
        eng.begin_restore([1], [p1], [lat1])
        eng.advance_restores(1)          # partially advanced lane
        assert eng.restoring_uids == [1]
        aborted = eng.abort_restore(1)
        assert aborted == [1]
        assert eng.restoring_uids == []
        assert eng.pending_restore_chunks == 0
        assert eng.state.free_blocks == free_before_lane
        assert eng.state.get_sequence(1) is None
        # unknown uid is a no-op
        assert eng.abort_restore(99) == []
        # the lane can be re-begun from the same payload and completes
        eng.restore_kv([1], [p1], [lat1])
        logits, _ = eng.put([1], [[3]])
        assert np.asarray(logits).shape[0] == 1

    def test_injected_ship_fault_is_retry_safe(self, tiny_model):
        """A faulted chunk ship surfaces from advance_restores; simply
        calling it again resumes from the same chunk and the restored
        logits equal the fault-free run's (no skipped/doubled chunk)."""
        from hcache_deepspeed_tpu.resilience import (FaultPlan,
                                                     FaultRule,
                                                     InjectedFault,
                                                     injected)
        cfg, params = tiny_model
        rng = np.random.default_rng(12)

        def run(plan):
            eng = build_engine(cfg, params)
            p0, p1, lat1 = _harvest(cfg, eng, np.random.default_rng(12))
            ctx = plan and injected(plan)
            faults = 0
            ticket = eng.begin_restore([1], [p1], [lat1])
            if ctx:
                ctx.__enter__()
            try:
                while not ticket.done:
                    try:
                        eng.advance_restores(1)
                    except InjectedFault:
                        faults += 1
            finally:
                if ctx:
                    ctx.__exit__(None, None, None)
            logits, _ = eng.put([1], [[3]])
            return np.asarray(logits), faults

        clean, n0 = run(None)
        plan = FaultPlan(rules=[FaultRule("restore.replay",
                                          at_hits=(2,))])
        faulted, n1 = run(plan)
        assert n0 == 0 and n1 == 1
        np.testing.assert_array_equal(clean, faulted)

    def test_put_fault_site_blames_last_uid(self, tiny_model):
        from hcache_deepspeed_tpu.resilience import (FaultPlan,
                                                     FaultRule,
                                                     InjectedFault,
                                                     injected)
        cfg, params = tiny_model
        eng = build_engine(cfg, params)
        rng = np.random.default_rng(13)
        p = list(map(int, rng.integers(0, cfg.vocab_size, 8)))
        with injected(FaultPlan(rules=[
                FaultRule("engine.prefill", at_hits=(1,))])):
            with pytest.raises(InjectedFault) as ei:
                eng.put([4, 5], [p, p])
        assert ei.value.uid == 5
        # the fault fired before any state mutated: both uids untracked
        assert eng.state.get_sequence(4) is None
        assert eng.state.get_sequence(5) is None
        logits, _ = eng.put([4, 5], [p, p])   # clean retry succeeds
        assert np.asarray(logits).shape[0] == 2
