"""Reference analog: ``tests/unit/inference/v2/ragged/test_blocked_allocator.py``."""

import pytest

from hcache_deepspeed_tpu.inference.ragged import BlockedAllocator


class TestBlockedAllocator:

    def test_allocate_and_free(self):
        alloc = BlockedAllocator(16)
        assert alloc.free_blocks == 16
        a = alloc.allocate(4)
        assert len(a) == 4 and len(set(a)) == 4
        assert alloc.free_blocks == 12
        b = alloc.allocate(12)
        assert alloc.free_blocks == 0
        assert not set(a) & set(b)
        alloc.free(a)
        assert alloc.free_blocks == 4
        c = alloc.allocate(4)
        assert sorted(c) == sorted(a)

    @pytest.mark.parametrize("bad", [0, -1])
    def test_bad_sizes(self, bad):
        alloc = BlockedAllocator(4)
        with pytest.raises(ValueError):
            alloc.allocate(bad)
        with pytest.raises(ValueError):
            BlockedAllocator(bad)

    def test_overallocate(self):
        alloc = BlockedAllocator(4)
        with pytest.raises(ValueError, match="only 4 free"):
            alloc.allocate(5)

    def test_double_free(self):
        alloc = BlockedAllocator(4)
        blocks = alloc.allocate(2)
        alloc.free(blocks)
        with pytest.raises(ValueError, match="double free"):
            alloc.free(blocks)

    def test_invalid_free(self):
        alloc = BlockedAllocator(4)
        with pytest.raises(ValueError, match="invalid block"):
            alloc.free([7])
