"""Prompt-lookup speculative decoding (beyond-reference: FastGen has no
speculative path). Greedy-exact by construction — every test's ground
truth is the engine's own token-by-token greedy decode."""

import jax
import numpy as np
import pytest

from hcache_deepspeed_tpu.inference import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig)
from hcache_deepspeed_tpu.models.llama import LlamaForCausalLM, llama_tiny


@pytest.fixture(scope="module")
def tiny_model():
    cfg = llama_tiny(max_positions=256, use_flash=False)
    model = LlamaForCausalLM(cfg)
    batch = {"input_ids": np.zeros((1, 8), np.int32)}
    params = model.init(jax.random.PRNGKey(0), batch, train=False)["params"]
    return cfg, model, params


def make_engine(cfg, params, blocks=48, latents=False):
    return InferenceEngineV2(
        cfg, params,
        config=RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 8,
                           "max_ragged_batch_size": 512,
                           "max_ragged_sequence_count": 4,
                           "max_context": 256},
            kv_cache={"block_size": 16, "num_blocks": blocks,
                      "cache_dtype": "float32"},
            hcache={"enable_latents": latents}))


def greedy_reference(engine, prompt, n):
    """Token-by-token greedy via the public generate()."""
    [out] = engine.generate([prompt], max_new_tokens=n)
    return out


class TestLookupDraft:

    def test_draft_from_repeat(self):
        hist = [1, 2, 3, 9, 1, 2, 3]
        d = InferenceEngineV2._lookup_draft(hist, ngram=2, k=4)
        # trailing [2, 3] matched at positions 1-2; following tokens
        assert d == [9, 1, 2, 3]

    def test_no_match(self):
        assert InferenceEngineV2._lookup_draft(
            [1, 2, 3, 4, 5], ngram=2, k=4) == []

    def test_most_recent_match_wins(self):
        hist = [7, 8, 1, 7, 8, 2, 7, 8]
        d = InferenceEngineV2._lookup_draft(hist, ngram=2, k=1)
        assert d == [2]

    def test_short_history(self):
        assert InferenceEngineV2._lookup_draft([5], ngram=2, k=4) == []


class TestLookupDecoding:

    def test_matches_greedy_exactly(self, tiny_model):
        cfg, _, params = tiny_model
        rng = np.random.default_rng(0)
        prompt = list(rng.integers(0, cfg.vocab_size, (24,)))
        ref_engine = make_engine(cfg, params)
        ref = greedy_reference(ref_engine, prompt, 20)
        engine = make_engine(cfg, params)
        [out], stats = engine.generate_lookup([prompt], max_new_tokens=20,
                                              ngram=2, max_draft=4)
        assert out == ref
        assert stats["tokens"] == 20
        # one prefill token + >=1 token per dispatch
        assert stats["dispatches"] <= 19

    def test_batched_matches_greedy(self, tiny_model):
        cfg, _, params = tiny_model
        rng = np.random.default_rng(3)
        prompts = [list(rng.integers(0, cfg.vocab_size, (n,)))
                   for n in (16, 24, 31)]
        refs = []
        for p in prompts:
            e = make_engine(cfg, params)
            refs.append(greedy_reference(e, p, 12))
        engine = make_engine(cfg, params)
        outs, _ = engine.generate_lookup(prompts, max_new_tokens=12,
                                         ngram=2, max_draft=4)
        assert outs == refs

    def test_accepts_on_repetitive_prompt(self, tiny_model):
        """A strongly periodic prompt makes the model's greedy
        continuation periodic too, so lookup drafts must land."""
        cfg, _, params = tiny_model
        cycle = [5, 11, 23, 7]
        prompt = (cycle * 12)[:44]
        engine = make_engine(cfg, params)
        [out], stats = engine.generate_lookup([prompt],
                                              max_new_tokens=24,
                                              ngram=2, max_draft=6)
        ref_engine = make_engine(cfg, params)
        assert out == greedy_reference(ref_engine, prompt, 24)
        assert stats["accepted"] > 0
        # speculative win: strictly fewer dispatches than tokens
        assert stats["dispatches"] < 23

    def test_eos_truncation_matches_greedy(self, tiny_model):
        cfg, _, params = tiny_model
        rng = np.random.default_rng(5)
        prompt = list(rng.integers(0, cfg.vocab_size, (20,)))
        ref_engine = make_engine(cfg, params)
        full = greedy_reference(ref_engine, prompt, 16)
        eos = full[4]   # force a truncation mid-stream
        engine = make_engine(cfg, params)
        [out], _ = engine.generate_lookup([prompt], max_new_tokens=16,
                                          ngram=2, max_draft=4,
                                          eos_token_id=eos)
        want = full[:full.index(eos) + 1]
        assert out == want

    def test_blocks_freed_after(self, tiny_model):
        cfg, _, params = tiny_model
        engine = make_engine(cfg, params)
        free0 = engine.state.free_blocks
        rng = np.random.default_rng(7)
        prompt = list(rng.integers(0, cfg.vocab_size, (24,)))
        engine.generate_lookup([prompt], max_new_tokens=8)
        assert engine.state.free_blocks == free0

    def test_gates(self, tiny_model):
        cfg, _, params = tiny_model
        engine = make_engine(cfg, params, latents=True)
        with pytest.raises(ValueError, match="enable_latents"):
            engine.generate_lookup([[1, 2, 3]])
        engine = make_engine(cfg, params)
        with pytest.raises(ValueError, match="max_new_tokens"):
            engine.generate_lookup([[1, 2, 3]], max_new_tokens=0)
        with pytest.raises(ValueError, match="ngram"):
            engine.generate_lookup([[1, 2, 3]], ngram=0)


class TestLookupFused:
    """The fully-on-device speculative loop must be bit-identical to
    both the host-driven lookup path and plain greedy decode."""

    def test_matches_greedy_and_host_lookup(self, tiny_model):
        cfg, _, params = tiny_model
        rng = np.random.default_rng(11)
        prompt = list(rng.integers(0, cfg.vocab_size, (24,)))
        ref = greedy_reference(make_engine(cfg, params), prompt, 20)
        host, _ = make_engine(cfg, params).generate_lookup(
            [prompt], max_new_tokens=20, ngram=2, max_draft=4)
        engine = make_engine(cfg, params)
        fused, stats = engine.generate_lookup_fused(
            [prompt], max_new_tokens=20, ngram=2, max_draft=4)
        assert fused[0] == ref == host[0]
        assert stats["tokens"] == 20
        assert stats["dispatches"] <= 19

    def test_batched_and_periodic(self, tiny_model):
        cfg, _, params = tiny_model
        rng = np.random.default_rng(13)
        cycle = [5, 11, 23, 7]
        prompts = [list(rng.integers(0, cfg.vocab_size, (20,))),
                   (cycle * 12)[:44],
                   list(rng.integers(0, cfg.vocab_size, (31,)))]
        refs = [greedy_reference(make_engine(cfg, params), p, 16)
                for p in prompts]
        engine = make_engine(cfg, params)
        outs, stats = engine.generate_lookup_fused(
            prompts, max_new_tokens=16, ngram=2, max_draft=6)
        assert outs == refs
        assert stats["accepted"] > 0       # the periodic lane lands
        # iteration count is batch-max: the non-accepting random lanes
        # still bound it by max_new-1
        assert stats["dispatches"] <= 15

    def test_periodic_alone_needs_fewer_dispatches(self, tiny_model):
        cfg, _, params = tiny_model
        cycle = [5, 11, 23, 7]
        prompt = (cycle * 12)[:44]
        ref = greedy_reference(make_engine(cfg, params), prompt, 24)
        engine = make_engine(cfg, params)
        [out], stats = engine.generate_lookup_fused(
            [prompt], max_new_tokens=24, ngram=2, max_draft=6)
        assert out == ref
        assert stats["accepted"] > 0
        assert stats["dispatches"] < 23    # strictly beats 1 token/step

    def test_eos_matches_host_lookup(self, tiny_model):
        cfg, _, params = tiny_model
        rng = np.random.default_rng(17)
        prompt = list(rng.integers(0, cfg.vocab_size, (20,)))
        full = greedy_reference(make_engine(cfg, params), prompt, 16)
        eos = full[5]
        host, _ = make_engine(cfg, params).generate_lookup(
            [prompt], max_new_tokens=16, ngram=2, max_draft=4,
            eos_token_id=eos)
        engine = make_engine(cfg, params)
        fused, _ = engine.generate_lookup_fused(
            [prompt], max_new_tokens=16, ngram=2, max_draft=4,
            eos_token_id=eos)
        assert fused == host

    def test_gpt2_trunk_family(self):
        """The tail-logits forward lives in the shared trunk — verify
        the gpt2-trunk family (learned positions, LayerNorm, tied head
        via embed.T) decodes speculative-exact too."""
        from hcache_deepspeed_tpu.models.gpt2 import (GPT2LMHeadModel,
                                                      gpt2_tiny)
        gcfg = gpt2_tiny(n_positions=256, use_flash=False)
        gmodel = GPT2LMHeadModel(gcfg)
        rng = np.random.default_rng(23)
        batch = {"input_ids": np.zeros((1, 8), np.int32)}
        gparams = gmodel.init(jax.random.PRNGKey(0), batch)["params"]
        prompt = list(rng.integers(0, gcfg.vocab_size, (24,)))

        def engine():
            return InferenceEngineV2(
                gcfg, gparams,
                config=RaggedInferenceEngineConfig(
                    state_manager={"max_tracked_sequences": 8,
                                   "max_ragged_batch_size": 512,
                                   "max_ragged_sequence_count": 4,
                                   "max_context": 256},
                    kv_cache={"block_size": 16, "num_blocks": 48,
                              "cache_dtype": "float32"},
                    hcache={"enable_latents": False}))

        ref = greedy_reference(engine(), prompt, 14)
        host, _ = engine().generate_lookup([prompt], max_new_tokens=14,
                                           ngram=2, max_draft=4)
        fused, _ = engine().generate_lookup_fused(
            [prompt], max_new_tokens=14, ngram=2, max_draft=4)
        assert host[0] == ref
        assert fused[0] == ref

    def test_int8_weights_compose(self, tiny_model):
        """Weight-only int8 serving + speculative decoding: the trunk
        dequantizes per layer inside the scan either way, so both
        lookup paths must match the quantized engine's own greedy
        decode exactly."""
        cfg, _, params = tiny_model

        def q_engine():
            return InferenceEngineV2(
                cfg, params,
                config=RaggedInferenceEngineConfig(
                    state_manager={"max_tracked_sequences": 8,
                                   "max_ragged_batch_size": 512,
                                   "max_ragged_sequence_count": 4,
                                   "max_context": 256},
                    kv_cache={"block_size": 16, "num_blocks": 48,
                              "cache_dtype": "float32"},
                    quantization={"enabled": True, "bits": 8,
                                  "group_size": 64, "min_size": 1024},
                    hcache={"enable_latents": False}))

        rng = np.random.default_rng(29)
        prompt = list(rng.integers(0, cfg.vocab_size, (24,)))
        want = greedy_reference(q_engine(), prompt, 12)
        host, _ = q_engine().generate_lookup([prompt], max_new_tokens=12,
                                             ngram=2, max_draft=4)
        fused, _ = q_engine().generate_lookup_fused(
            [prompt], max_new_tokens=12, ngram=2, max_draft=4)
        assert host[0] == want
        assert fused[0] == want

    def test_blocks_freed_and_reusable(self, tiny_model):
        cfg, _, params = tiny_model
        engine = make_engine(cfg, params)
        free0 = engine.state.free_blocks
        rng = np.random.default_rng(19)
        prompt = list(rng.integers(0, cfg.vocab_size, (24,)))
        engine.generate_lookup_fused([prompt], max_new_tokens=8)
        assert engine.state.free_blocks == free0
        # engine still serves normally afterwards
        [out] = engine.generate([prompt], max_new_tokens=4)
        assert len(out) == 4
