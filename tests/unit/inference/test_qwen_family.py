"""Qwen family: the llama trunk with q/k/v projection biases
(reference: engine_factory.py qwen/qwen2 policies; HF Qwen2 uses
attention biases)."""

import jax
import numpy as np
import pytest

from hcache_deepspeed_tpu.inference import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig,
                                            build_hf_engine)
from hcache_deepspeed_tpu.models.llama import (LlamaForCausalLM, llama_tiny)


@pytest.fixture(scope="module")
def tiny_qwen():
    cfg = llama_tiny(max_positions=128, use_flash=False,
                     attention_bias=True)
    model = LlamaForCausalLM(cfg)
    batch = {"input_ids": np.zeros((1, 8), np.int32)}
    params = model.init(jax.random.PRNGKey(0), batch, train=False)["params"]
    return cfg, model, params


def full_logits(model, params, tokens):
    out = model.apply({"params": params},
                      {"input_ids": np.asarray(tokens, np.int32)[None]},
                      train=False, return_logits=True)
    return np.asarray(out)[0]


def test_params_have_biases(tiny_qwen):
    _, _, params = tiny_qwen
    assert "bias" in params["layers_0"]["self_attn"]["q_proj"]


def test_prefill_decode_parity(tiny_qwen):
    cfg, model, params = tiny_qwen
    engine = InferenceEngineV2(
        cfg, params,
        config=RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 4, "max_context": 128},
            kv_cache={"block_size": 16, "num_blocks": 24,
                      "cache_dtype": "float32"}))
    rng = np.random.default_rng(0)
    tokens = list(rng.integers(0, cfg.vocab_size, (10,)))
    logits, _ = engine.put([1], [tokens])
    np.testing.assert_allclose(logits[0],
                               full_logits(model, params, tokens)[-1],
                               atol=2e-2)
    nxt = int(np.argmax(logits[0]))
    tokens.append(nxt)
    dec, _ = engine.put([1], [[nxt]])
    np.testing.assert_allclose(dec[0],
                               full_logits(model, params, tokens)[-1],
                               atol=2e-2)


def test_hf_factory_qwen_v1_translates_keys(tiny_qwen):
    # qwen (v1) spells context/eps/rope in its own keys and reports a
    # doubled SwiGLU intermediate_size; the adapter must translate all of
    # them onto the llama trunk and force qkv biases on
    cfg, _, _ = tiny_qwen
    hf = {"model_type": "qwen", "vocab_size": cfg.vocab_size,
          "hidden_size": cfg.hidden_size,
          "intermediate_size": cfg.intermediate_size * 2,
          "num_hidden_layers": cfg.n_layer,
          "num_attention_heads": cfg.n_head,
          "seq_length": 128,
          # non-default values so the key translation is actually
          # exercised (defaults would mask a wrong .get key)
          "layer_norm_epsilon": 1e-5,
          "rotary_emb_base": 5e5,
          "torch_dtype": "float32"}
    import dataclasses

    from hcache_deepspeed_tpu.inference.factory import MODEL_FAMILIES
    mcfg = dataclasses.replace(MODEL_FAMILIES["qwen"](hf),
                               use_flash=cfg.use_flash)
    assert mcfg.attention_bias
    assert mcfg.intermediate_size == cfg.intermediate_size
    assert mcfg.max_positions == 128
    assert mcfg.rms_norm_eps == 1e-5
    assert mcfg.rope_theta == 5e5
    assert mcfg.n_kv_head == mcfg.n_head  # v1 is MHA (fixture is GQA,
    # so params are initialised fresh from the translated config)
    model = LlamaForCausalLM(mcfg)
    params = model.init(jax.random.PRNGKey(1),
                        {"input_ids": np.zeros((1, 8), np.int32)},
                        train=False)["params"]
    engine = build_hf_engine(
        hf, params,
        engine_config=RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 4, "max_context": 128},
            kv_cache={"block_size": 16, "num_blocks": 24,
                      "cache_dtype": "float32"}))
    rng = np.random.default_rng(1)
    tokens = list(rng.integers(0, cfg.vocab_size, (6,)))
    logits, _ = engine.put([1], [tokens])
    np.testing.assert_allclose(
        logits[0], full_logits(model, params, tokens)[-1], atol=2e-2)


def test_hf_factory_qwen2_sets_bias(tiny_qwen):
    cfg, _, params = tiny_qwen
    hf = {"model_type": "qwen2", "vocab_size": cfg.vocab_size,
          "hidden_size": cfg.hidden_size,
          "intermediate_size": cfg.intermediate_size,
          "num_hidden_layers": cfg.n_layer,
          "num_attention_heads": cfg.n_head,
          "num_key_value_heads": cfg.n_kv_head,
          "max_position_embeddings": 128,
          "torch_dtype": "float32"}
    engine = build_hf_engine(
        hf, params,
        engine_config=RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 4, "max_context": 128},
            kv_cache={"block_size": 16, "num_blocks": 24}))
    assert engine.model.cfg.attention_bias
    logits, _ = engine.put([1], [[1, 2, 3]])
    assert np.isfinite(np.asarray(logits)).all()
