"""Tensor-parallel fused-int8 quantized serving (the 70B-class int8 TP
mode): col shards split N with their scales, row shards split K on
group boundaries — logits must match the single-chip fused engine."""

import jax
import numpy as np
import pytest

from hcache_deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
from hcache_deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
from hcache_deepspeed_tpu.models.gpt2 import GPT2LMHeadModel, gpt2_tiny
from hcache_deepspeed_tpu.models.llama import LlamaForCausalLM, llama_tiny
from hcache_deepspeed_tpu.parallel import topology as topo_mod


def _engine(cfg, params, topology=None, fused=True, enabled=True):
    quant = {}
    if enabled:
        quant = {"enabled": True, "bits": 8, "group_size": 32,
                 "min_size": 1024, "use_fused_kernel": fused}
    return InferenceEngineV2(
        cfg, params, topology=topology,
        config=RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 8,
                           "max_context": 128},
            kv_cache={"block_size": 16, "num_blocks": 24,
                      "cache_dtype": "float32"},
            quantization=quant))


@pytest.fixture
def tp_topo(eight_devices):
    topo = topo_mod.initialize_topology(
        topo_mod.TopologySpec(data=4, tensor=2))
    yield topo
    topo_mod.reset_topology()


def _init(model):
    batch = {"input_ids": np.zeros((1, 8), np.int32)}
    return model.init(jax.random.PRNGKey(0), batch,
                      train=False)["params"]


@pytest.mark.parametrize("family", ["llama", "gpt2"])
def test_tp_fused_matches_single_chip_fused(tp_topo, family):
    if family == "llama":
        # tied head: under TP the untied head stays full precision
        # (flat-layout groups straddle the vocab shard) while the
        # single-chip engine quantizes it — tying removes the one
        # intentional layout difference so logits compare exactly
        cfg = llama_tiny(hidden_size=128, intermediate_size=256,
                         max_positions=128, use_flash=False,
                         tie_word_embeddings=True)
        params = _init(LlamaForCausalLM(cfg))
    else:
        cfg = gpt2_tiny(n_embd=128, n_positions=128, use_flash=False)
        params = _init(GPT2LMHeadModel(cfg))
    ref = _engine(cfg, params)                       # single-chip fused
    tp = _engine(cfg, params, topology=tp_topo)      # tp=2 fused
    from hcache_deepspeed_tpu.ops.quantized_matmul import \
        MatmulQuantizedTensor
    leaves = jax.tree.leaves(
        tp.model.params,
        is_leaf=lambda x: isinstance(x, MatmulQuantizedTensor))
    assert any(isinstance(l, MatmulQuantizedTensor) for l in leaves)

    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, (12,)).tolist()
    lr, _ = ref.put([1], [prompt])
    lt, _ = tp.put([1], [prompt])
    np.testing.assert_allclose(np.asarray(lr), np.asarray(lt), atol=2e-4)
    tok = int(np.argmax(np.asarray(lr)[0]))
    for _ in range(3):
        lr, _ = ref.put([1], [[tok]])
        lt, _ = tp.put([1], [[tok]])
        np.testing.assert_allclose(np.asarray(lr), np.asarray(lt),
                                   atol=2e-4)
        tok = int(np.argmax(np.asarray(lr)[0]))


def test_dequant_mode_tp_matches_single_chip(tp_topo):
    """Plain-int8 TP serving (formerly rejected): trunk kernels use the
    k-major MatmulQuantizedTensor layout in both modes now, so col/row
    shards stay group-pure and dequant-mode TP logits match the
    single-chip dequant engine."""
    cfg = llama_tiny(hidden_size=128, intermediate_size=256,
                     max_positions=128, use_flash=False,
                     tie_word_embeddings=True)
    params = _init(LlamaForCausalLM(cfg))
    ref = _engine(cfg, params, fused=False)
    tp = _engine(cfg, params, topology=tp_topo, fused=False)
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, cfg.vocab_size, (12,)).tolist()
    lr, _ = ref.put([1], [prompt])
    lt, _ = tp.put([1], [prompt])
    np.testing.assert_allclose(np.asarray(lr), np.asarray(lt), atol=2e-4)
    tok = int(np.argmax(np.asarray(lr)[0]))
    lr, _ = ref.put([1], [[tok]])
    lt, _ = tp.put([1], [[tok]])
    np.testing.assert_allclose(np.asarray(lr), np.asarray(lt), atol=2e-4)


def test_moe_tp_quantized_rejected(tp_topo):
    from hcache_deepspeed_tpu.models.mixtral import (MixtralForCausalLM,
                                                     mixtral_tiny)
    cfg = mixtral_tiny(hidden_size=64, intermediate_size=128,
                       max_positions=128, use_flash=False, dropless=True)
    params = _init(MixtralForCausalLM(cfg))
    with pytest.raises(NotImplementedError, match="MoE"):
        _engine(cfg, params, topology=tp_topo)
