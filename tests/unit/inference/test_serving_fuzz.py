"""Randomized differential test of the serving engine lifecycle.

Reference analog: the reference tests each ragged-engine operation in
isolation (``tests/unit/inference/v2``); nothing there exercises random
*interleavings* of scheduling, decode, eviction, HCache restore and KV
suspend/resume under block-pool pressure. Every decode's logits are
checked against a full-context recompute through the training model, so
any cross-sequence KV corruption, stale block reuse after flush, or
restore/resume bookkeeping drift surfaces as a numeric mismatch at the
exact op that broke it.
"""

import numpy as np
import pytest

from hcache_deepspeed_tpu.inference import SchedulingResult

from .test_engine_v2 import full_logits, make_engine, tiny_model  # noqa: F401

MAX_CTX = 96


class _Shadow:
    """Host-side ground truth for one sequence."""

    def __init__(self, tokens, latents):
        self.tokens = list(tokens)
        self.latents = latents          # [L, T, H] accumulated
        self.alive = True
        self.suspended = False

    def absorb(self, new_tokens, new_latents):
        self.tokens.extend(int(t) for t in np.atleast_1d(new_tokens))
        if new_latents is not None:
            self.latents = new_latents if self.latents is None else \
                np.concatenate([self.latents, new_latents], axis=1)


@pytest.mark.slow
class TestServingLifecycleFuzz:

    def _check_decode(self, model, params, sh, logits):
        ref = full_logits(model, params, sh.tokens)
        np.testing.assert_allclose(logits, ref[-1], atol=2e-2)

    def test_random_interleavings_match_recompute(self, tiny_model):
        cfg, model, params = tiny_model
        engine = make_engine(
            cfg, params,
            state_manager={"max_tracked_sequences": 6,
                           "max_ragged_batch_size": 128,
                           "max_ragged_sequence_count": 4,
                           "max_context": MAX_CTX},
            # small pool: scheduling pressure is part of the test
            kv_cache={"block_size": 16, "num_blocks": 30,
                      "cache_dtype": "float32"})
        rng = np.random.default_rng(42)
        shadows = {}           # uid -> _Shadow (alive or restorable)
        next_uid = 0
        counts = {"new": 0, "decode": 0, "flush": 0, "restore": 0,
                  "suspend": 0, "resume": 0, "rejected": 0}

        def alive(pred=lambda s: True):
            return [u for u, s in shadows.items() if s.alive and pred(s)]

        for _ in range(90):
            op = rng.choice(["new", "decode", "decode", "decode", "flush",
                             "flush", "restore", "restore", "suspend",
                             "resume"])
            if op == "new" and len(alive()) < 4:
                prompt = rng.integers(0, cfg.vocab_size,
                                      (int(rng.integers(3, 24)),))
                if engine.can_schedule([next_uid], [len(prompt)]) != \
                        SchedulingResult.Success:
                    counts["rejected"] += 1
                    continue
                logits, latents = engine.put([next_uid], [prompt])
                sh = _Shadow(prompt, latents[0])
                shadows[next_uid] = sh
                self._check_decode(model, params, sh, logits[0])
                counts["new"] += 1
                next_uid += 1
            elif op == "decode":
                cands = alive(lambda s: not s.suspended
                              and len(s.tokens) < MAX_CTX - 1)
                if not cands:
                    continue
                uid = int(rng.choice(cands))
                sh = shadows[uid]
                tok = int(rng.integers(0, cfg.vocab_size))
                if engine.can_schedule([uid], [1]) != \
                        SchedulingResult.Success:
                    counts["rejected"] += 1
                    continue
                logits, latents = engine.put([uid], [[tok]])
                sh.absorb([tok], latents[0])
                self._check_decode(model, params, sh, logits[0])
                counts["decode"] += 1
            elif op == "flush":
                cands = alive(lambda s: not s.suspended)
                if not cands:
                    continue
                uid = int(rng.choice(cands))
                engine.flush(uid)
                assert engine.state.get_sequence(uid) is None
                shadows[uid].alive = False
                counts["flush"] += 1
            elif op == "restore":
                cands = [u for u, s in shadows.items()
                         if not s.alive and s.latents is not None
                         and len(s.tokens) < MAX_CTX - 1]
                if not cands or len(alive()) >= 4:
                    continue
                uid = int(rng.choice(cands))
                sh = shadows[uid]
                if engine.can_schedule([uid], [len(sh.tokens)]) != \
                        SchedulingResult.Success:
                    counts["rejected"] += 1
                    continue
                engine.restore_kv([uid], [sh.tokens], [sh.latents])
                assert engine.state.get_sequence(uid).seen_tokens == \
                    len(sh.tokens)
                sh.alive = True
                sh.suspended = False
                counts["restore"] += 1
            elif op == "suspend":
                cands = alive(lambda s: not s.suspended)
                if not cands:
                    continue
                uid = int(rng.choice(cands))
                engine.suspend_sequence(uid)
                shadows[uid].suspended = True
                # writes against a suspended sequence must be refused
                with pytest.raises(Exception):
                    engine.put([uid], [[0]])
                counts["suspend"] += 1
            elif op == "resume":
                cands = alive(lambda s: s.suspended)
                if not cands:
                    continue
                uid = int(rng.choice(cands))
                engine.resume_sequence(uid)
                shadows[uid].suspended = False
                # the first decode after resume proves the KV round-trip
                sh = shadows[uid]
                if len(sh.tokens) < MAX_CTX - 1:
                    tok = int(rng.integers(0, cfg.vocab_size))
                    logits, latents = engine.put([uid], [[tok]])
                    sh.absorb([tok], latents[0])
                    self._check_decode(model, params, sh, logits[0])
                counts["resume"] += 1

        # the run must actually have exercised the lifecycle
        assert counts["new"] >= 3 and counts["decode"] >= 8, counts
        assert counts["flush"] >= 1 and counts["restore"] >= 1, counts
        assert counts["suspend"] >= 1 and counts["resume"] >= 1, counts

        # drain: every tracked sequence still flushes cleanly and the
        # block pool returns to empty (no leaked blocks)
        for uid in alive():
            if shadows[uid].suspended:
                engine.resume_sequence(uid)
            engine.flush(uid)
        assert engine.state.n_tracked_sequences == 0
