"""Tensor-parallel serving for the GPT-2/OPT trunk (the fused c_attn
splits into q/k/v at load so column shards stay head-aligned; row
biases add once after the psum; tied embedding is vocab-parallel)."""

import jax
import numpy as np
import pytest

from hcache_deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
from hcache_deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
from hcache_deepspeed_tpu.models.gpt2 import GPT2LMHeadModel, gpt2_tiny
from hcache_deepspeed_tpu.models.opt import OPTForCausalLM, opt_tiny
from hcache_deepspeed_tpu.parallel import topology as topo_mod


def _engine(cfg, params, topology=None):
    return InferenceEngineV2(
        cfg, params, topology=topology,
        config=RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 8,
                           "max_context": 128},
            kv_cache={"block_size": 16, "num_blocks": 24,
                      "cache_dtype": "float32"}))


@pytest.fixture
def tp_topo(eight_devices):
    topo = topo_mod.initialize_topology(
        topo_mod.TopologySpec(data=4, tensor=2))
    yield topo
    topo_mod.reset_topology()


def _init(model):
    batch = {"input_ids": np.zeros((1, 8), np.int32)}
    return model.init(jax.random.PRNGKey(0), batch,
                      train=False)["params"]


def _parity(cfg, params, tp_topo):
    ref = _engine(cfg, params)
    tp = _engine(cfg, params, topology=tp_topo)
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, cfg.vocab_size, (14,)).tolist()
    lr, _ = ref.put([1], [prompt])
    lt, _ = tp.put([1], [prompt])
    np.testing.assert_allclose(np.asarray(lr), np.asarray(lt), atol=2e-4)
    tok = int(np.argmax(np.asarray(lr)[0]))
    for _ in range(3):
        lr, _ = ref.put([1], [[tok]])
        lt, _ = tp.put([1], [[tok]])
        np.testing.assert_allclose(np.asarray(lr), np.asarray(lt),
                                   atol=2e-4)
        tok = int(np.argmax(np.asarray(lr)[0]))
    # HCache restore under TP
    lr2, latents = ref.put([2], [prompt])
    lt2, latents_t = tp.put([2], [prompt])
    tp.flush(2)
    tp.restore_kv([2], [prompt], [latents_t[0]])
    nxt = int(np.argmax(np.asarray(lr2)[0]))
    dr, _ = ref.put([2], [[nxt]])
    dt, _ = tp.put([2], [[nxt]])
    np.testing.assert_allclose(np.asarray(dr), np.asarray(dt), atol=2e-4)


def test_gpt2_tp_parity(tp_topo):
    cfg = gpt2_tiny(use_flash=False)
    _parity(cfg, _init(GPT2LMHeadModel(cfg)), tp_topo)


def test_opt_tp_parity(tp_topo):
    cfg = opt_tiny(use_flash=False)
    _parity(cfg, _init(OPTForCausalLM(cfg)), tp_topo)


def test_split_cattn_sharded_by_head(tp_topo):
    cfg = gpt2_tiny(use_flash=False)
    tp = _engine(cfg, _init(GPT2LMHeadModel(cfg)), topology=tp_topo)
    a = tp.model.params["layers"]["attn"]
    assert "tensor" in str(a["q_proj"]["kernel"].sharding.spec)
    assert "tensor" in str(a["q_proj"]["bias"].sharding.spec)
    # row bias replicated (added once after the psum)
    assert "tensor" not in str(a["c_proj"]["bias"].sharding.spec)
    # tied embedding vocab-row sharded
    assert "tensor" in str(tp.model.params["embed"].sharding.spec)