"""Qwen2-MoE family: shared expert + raw top-k gate mass
(reference: the qwen2-moe policy in engine_factory.py:69;
HF Qwen2MoeSparseMoeBlock semantics)."""

import jax
import numpy as np
import pytest

from hcache_deepspeed_tpu.inference import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig,
                                            build_hf_engine)
from hcache_deepspeed_tpu.inference.model_moe import PagedMoEModel
from hcache_deepspeed_tpu.models.mixtral import (MixtralForCausalLM,
                                                 Qwen2MoeConfig,
                                                 qwen2_moe_tiny)


@pytest.fixture(scope="module")
def tiny_qwen2_moe():
    cfg = qwen2_moe_tiny(max_positions=128, use_flash=False)
    model = MixtralForCausalLM(cfg)
    batch = {"input_ids": np.zeros((1, 8), np.int32)}
    params = model.init(jax.random.PRNGKey(0), batch, train=False)["params"]
    return cfg, model, params


def make_engine(cfg, params):
    return InferenceEngineV2(
        cfg, params,
        config=RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 8, "max_context": 128},
            kv_cache={"block_size": 16, "num_blocks": 24,
                      "cache_dtype": "float32"}))


def full_logits(model, params, tokens):
    out = model.apply({"params": params},
                      {"input_ids": np.asarray(tokens, np.int32)[None]},
                      train=False, return_logits=True)
    return np.asarray(out)[0]


def test_params_carry_shared_expert_and_biases(tiny_qwen2_moe):
    cfg, _, params = tiny_qwen2_moe
    moe = params["layers_0"]["mlp"]["moe"]
    assert "shared_gate_proj" in moe and "shared_expert_gate" in moe
    assert "bias" in params["layers_0"]["self_attn"]["q_proj"]


def test_training_model_trains(tiny_qwen2_moe):
    cfg, model, params = tiny_qwen2_moe
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (2, 16),
                                       dtype=np.int32)}

    def loss_fn(p):
        return model.apply({"params": p}, batch, train=True)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    sg = grads["layers_0"]["mlp"]["moe"]["shared_expert_gate"]["kernel"]
    assert float(np.abs(np.asarray(sg)).sum()) > 0


def test_prefill_decode_parity(tiny_qwen2_moe):
    cfg, model, params = tiny_qwen2_moe
    engine = make_engine(cfg, params)
    assert isinstance(engine.model, PagedMoEModel)
    rng = np.random.default_rng(1)
    tokens = list(rng.integers(0, cfg.vocab_size, (11,)))
    logits, _ = engine.put([1], [tokens])
    np.testing.assert_allclose(logits[0],
                               full_logits(model, params, tokens)[-1],
                               atol=2e-2)
    for _ in range(4):
        nxt = int(np.argmax(logits[0]))
        tokens.append(nxt)
        logits, _ = engine.put([1], [[nxt]])
        np.testing.assert_allclose(
            logits[0], full_logits(model, params, tokens)[-1], atol=2e-2)


def test_raw_gate_mass_differs_from_renormalized(tiny_qwen2_moe):
    """norm_topk_prob=False must actually change the math (guards against
    the flag silently defaulting to mixtral renormalization)."""
    import dataclasses
    cfg, model, params = tiny_qwen2_moe
    cfg_renorm = dataclasses.replace(cfg, norm_topk_prob=True)
    model2 = MixtralForCausalLM(cfg_renorm)
    rng = np.random.default_rng(2)
    tokens = list(rng.integers(0, cfg.vocab_size, (9,)))
    a = full_logits(model, params, tokens)
    b = full_logits(model2, params, tokens)
    assert np.abs(a - b).max() > 1e-4


def test_hf_factory_qwen2_moe(tiny_qwen2_moe):
    cfg, _, params = tiny_qwen2_moe
    hf = {"model_type": "qwen2_moe", "vocab_size": cfg.vocab_size,
          "hidden_size": cfg.hidden_size,
          "moe_intermediate_size": cfg.intermediate_size,
          "shared_expert_intermediate_size":
              cfg.shared_expert_intermediate_size,
          "num_hidden_layers": cfg.n_layer,
          "num_attention_heads": cfg.n_head,
          "num_key_value_heads": cfg.n_kv_head,
          "max_position_embeddings": 128,
          "num_experts": cfg.num_experts,
          "num_experts_per_tok": cfg.top_k,
          "norm_topk_prob": False,
          "rms_norm_eps": cfg.rms_norm_eps,
          "rope_theta": cfg.rope_theta,
          "torch_dtype": "float32"}
    engine = build_hf_engine(
        hf, params,
        engine_config=RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 4,
                           "max_context": 128},
            kv_cache={"block_size": 16, "num_blocks": 24}))
    assert isinstance(engine.model.cfg, Qwen2MoeConfig)
    assert not engine.model.cfg.norm_topk_prob
    logits, _ = engine.put([1], [[1, 2, 3]])
    assert np.isfinite(np.asarray(logits)).all()
