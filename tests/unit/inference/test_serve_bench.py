"""Serving benchmark smoke (reference: the FastGen bench harness) —
keeps the measurement tool itself green across engine changes."""

from hcache_deepspeed_tpu.inference.benchmark import run


def test_serve_bench_all_modes():
    for kw in ({}, {"quantize": "int8"}, {"prefill_chunk": 32}):
        results = run(model_size="tiny", max_context=128, prompt_len=32,
                      decode_steps=4, batches=(1,), **kw)
        phases = {r["phase"] for r in results}
        assert "prefill" in phases and "decode" in phases
        assert "decode-context-scaling" in phases
        for r in results:
            if "tokens_per_sec" in r:
                assert r["tokens_per_sec"] > 0


def test_serve_bench_fused_mode():
    results = run(model_size="tiny", max_context=128, prompt_len=32,
                  decode_steps=4, batches=(1,), fused=True)
    phases = {r["phase"] for r in results}
    assert "decode-fused" in phases


def test_serve_bench_sweep():
    from hcache_deepspeed_tpu.inference.benchmark import run_sweep
    rows = run_sweep(model_size="tiny", max_context=128, prompt_len=16,
                     max_new=4, rates=(50.0,), n_requests=5, max_batch=4)
    (row,) = rows
    assert row["phase"] == "sweep"
    assert row["effective_rps"] > 0
    assert row["ttft_s"]["p50"] <= row["e2e_s"]["p50"]
    assert row["gen_tokens_per_sec"] > 0


def test_serve_bench_lookup_mode():
    results = run(model_size="tiny", max_context=128, prompt_len=32,
                  decode_steps=8, batches=(2,), lookup=True)
    rows = {r["phase"]: r for r in results}
    assert rows["decode-lookup"]["dispatches"] >= 1
    assert rows["decode-lookup"]["tokens_per_dispatch"] >= 1.0
    assert rows["decode-lookup-fused"]["device_steps"] >= 1
    assert rows["decode-lookup-fused"]["tokens_per_device_step"] >= 1.0


def test_serve_bench_sweep_fused():
    from hcache_deepspeed_tpu.inference.benchmark import run_sweep_fused
    rows = run_sweep_fused(model_size="tiny", max_context=128,
                           prompt_len=16, max_new=4, rates=(50.0,),
                           n_requests=5, max_batch=4)
    (row,) = rows
    assert row["phase"] == "sweep-fused"
    assert row["decode_path"] == "fused"
    assert row["effective_rps"] > 0
    assert row["waves"] >= 2   # 5 requests, max_batch 4
    assert row["gen_tokens_per_sec"] > 0


def test_serve_bench_restore_mode():
    from hcache_deepspeed_tpu.inference.benchmark import run_restore
    rows = run_restore(model_size="tiny", max_context=128, prompt_len=16,
                       batches=(1,))
    (row,) = rows
    assert row["phase"] == "hcache-restore"
    assert row["restore_kv_ms"] > 0 and row["prefill_recompute_ms"] > 0
