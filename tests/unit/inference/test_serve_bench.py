"""Serving benchmark smoke (reference: the FastGen bench harness) —
keeps the measurement tool itself green across engine changes."""

from hcache_deepspeed_tpu.inference.benchmark import run


def test_serve_bench_all_modes():
    for kw in ({}, {"quantize": "int8"}, {"prefill_chunk": 32}):
        results = run(model_size="tiny", max_context=128, prompt_len=32,
                      decode_steps=4, batches=(1,), **kw)
        phases = {r["phase"] for r in results}
        assert "prefill" in phases and "decode" in phases
        assert "decode-context-scaling" in phases
        for r in results:
            if "tokens_per_sec" in r:
                assert r["tokens_per_sec"] > 0
