"""Serving benchmark smoke (reference: the FastGen bench harness) —
keeps the measurement tool itself green across engine changes."""

import numpy as np
import pytest

from hcache_deepspeed_tpu.inference.benchmark import run


def test_serve_bench_all_modes():
    for kw in ({}, {"quantize": "int8"}, {"prefill_chunk": 32}):
        results = run(model_size="tiny", max_context=128, prompt_len=32,
                      decode_steps=4, batches=(1,), **kw)
        phases = {r["phase"] for r in results}
        assert "prefill" in phases and "decode" in phases
        assert "decode-context-scaling" in phases
        for r in results:
            if "tokens_per_sec" in r:
                assert r["tokens_per_sec"] > 0


def test_serve_bench_fused_mode():
    results = run(model_size="tiny", max_context=128, prompt_len=32,
                  decode_steps=4, batches=(1,), fused=True)
    phases = {r["phase"] for r in results}
    assert "decode-fused" in phases


def test_serve_bench_fused_oom_falls_back_to_host_decode(monkeypatch):
    """A fused-decode compile OOM (seen at 7B bf16 on a 16 GB chip:
    stacked-QKV layout copies) must not kill the measurement — the tool
    emits an error row and still produces host-driven decode numbers."""
    from hcache_deepspeed_tpu.inference.engine_v2 import InferenceEngineV2

    def boom(self, prompts, **kw):
        raise RuntimeError(
            "RESOURCE_EXHAUSTED: Ran out of memory in memory space hbm")

    monkeypatch.setattr(InferenceEngineV2, "generate_fused", boom)
    results = run(model_size="tiny", max_context=128, prompt_len=32,
                  decode_steps=4, batches=(1,), fused=True)
    phases = [r["phase"] for r in results]
    oom_rows = [r for r in results
                if r["phase"] == "decode-fused" and "error" in r]
    host_rows = [r for r in results
                 if r["phase"] == "decode" and "note" in r]
    assert oom_rows and host_rows
    assert host_rows[0]["tokens_per_sec"] > 0
    # context-scaling phase still runs after the fallback
    assert "decode-context-scaling" in phases


def test_serve_bench_sweep():
    from hcache_deepspeed_tpu.inference.benchmark import run_sweep
    rows = run_sweep(model_size="tiny", max_context=128, prompt_len=16,
                     max_new=4, rates=(50.0,), n_requests=5, max_batch=4)
    (row,) = rows
    assert row["phase"] == "sweep"
    assert row["effective_rps"] > 0
    assert row["ttft_s"]["p50"] <= row["e2e_s"]["p50"]
    assert row["gen_tokens_per_sec"] > 0


def test_serve_bench_lookup_mode():
    results = run(model_size="tiny", max_context=128, prompt_len=32,
                  decode_steps=8, batches=(2,), lookup=True)
    rows = {r["phase"]: r for r in results}
    assert rows["decode-lookup"]["dispatches"] >= 1
    assert rows["decode-lookup"]["tokens_per_dispatch"] >= 1.0
    assert rows["decode-lookup-fused"]["device_steps"] >= 1
    assert rows["decode-lookup-fused"]["tokens_per_device_step"] >= 1.0


def test_serve_bench_sweep_fused():
    from hcache_deepspeed_tpu.inference.benchmark import run_sweep_fused
    rows = run_sweep_fused(model_size="tiny", max_context=128,
                           prompt_len=16, max_new=4, rates=(50.0,),
                           n_requests=5, max_batch=4)
    (row,) = rows
    assert row["phase"] == "sweep-fused"
    assert row["decode_path"] == "fused"
    assert row["effective_rps"] > 0
    assert row["waves"] >= 2   # 5 requests, max_batch 4
    assert row["gen_tokens_per_sec"] > 0


def test_bench_model_sizes_trace():
    """The 1b/7b bench configs must build and trace (eval_shape — no
    weights materialized) with sane parameter counts, so a live-relay
    7B session can't die on a config bug."""
    import jax
    from hcache_deepspeed_tpu.models.llama import (LlamaConfig,
                                                   LlamaForCausalLM)
    from hcache_deepspeed_tpu.inference.benchmark import _MODEL_SIZES
    # exact arithmetic: per-layer 4h^2 + 3*h*ffn, plus two vocab
    # matrices (untied embed + head)
    sizes = {"1b": 1.35e9, "7b": 6.74e9}
    for name in sizes:
        assert name in _MODEL_SIZES, name
    for name in sizes:
        spec = _MODEL_SIZES[name]
        cfg = LlamaConfig(max_positions=512, dtype="bfloat16",
                          use_flash=False, **spec)
        model = LlamaForCausalLM(cfg)
        shapes = jax.eval_shape(
            lambda k: model.init(k, {"input_ids": np.zeros((1, 8),
                                                           np.int32)},
                                 train=False),
            jax.random.PRNGKey(0))
        n = sum(int(np.prod(x.shape))
                for x in jax.tree.leaves(shapes["params"]))
        assert abs(n - sizes[name]) / sizes[name] < 0.15, (name, n)


def test_serve_bench_restore_mode():
    from hcache_deepspeed_tpu.inference.benchmark import run_restore
    rows = run_restore(model_size="tiny", max_context=128, prompt_len=16,
                       batches=(1,))
    (row,) = rows
    assert row["phase"] == "hcache-restore"
    assert row["restore_kv_ms"] > 0 and row["prefill_recompute_ms"] > 0


def test_serve_bench_restore_marginal_mode():
    """Marginal decomposition: device replay cost vs link ship cost
    (chained dispatches, one sync — the high-latency-relay method)."""
    from hcache_deepspeed_tpu.inference.benchmark import \
        run_restore_marginal
    rows = run_restore_marginal(model_size="tiny", max_context=128,
                                prompt_len=16, batches=(1, 2), chain=3)
    assert len(rows) == 2
    for row in rows:
        assert row["phase"] == "hcache-restore-marginal"
        # CPU slope timings are noise-dominated on the tiny model — this
        # smoke asserts row shape/sanity, not magnitudes
        for key in ("replay_ms", "prefill_ms", "restore_e2e_ms",
                    "ship_ms"):
            assert row[key] >= 0, (key, row)
        assert row["link_gbps"] > 0


def test_serve_loop_mode(tmp_path):
    """serve_loop: the serving subsystem end-to-end over a Poisson
    trace — zero drops, percentile rows, and at least one
    preempt→suspend→restore_kv cycle with exact token parity (the
    runner raises on drops or parity failure). Virtual clock keeps the
    test deterministic and fast; the acceptance command runs the same
    path with the wall clock."""
    from hcache_deepspeed_tpu.inference.benchmark import run_serve_loop
    out = tmp_path / "serve_loop.jsonl"
    rows = run_serve_loop(model_size="tiny", n_requests=16, rps=100.0,
                          virtual_clock=True, out=str(out))
    summary = rows[-1]
    assert summary["phase"] == "serve-loop-summary"
    assert summary["dropped"] == 0
    assert summary["preemptions"] >= 1 and summary["restores"] >= 1
    assert summary["parity"]["checked"] >= 1
    assert summary["parity"]["ok"] == summary["parity"]["checked"]
    assert summary["ttft_s"]["count"] == 16
    assert summary["ttft_s"]["p90"] >= summary["ttft_s"]["p50"]
    assert summary["tpot_s"]["p50"] > 0
    per_req = [r for r in rows if r["phase"] == "serve-loop"]
    assert len(per_req) == 16
    assert all(r["state"] == "DONE" for r in per_req)
    # the artifact file mirrors the emitted rows
    import json as _json
    lines = [_json.loads(l) for l in out.read_text().splitlines()]
    assert len(lines) == len(rows)


def test_serve_loop_overlap_ratio_positive(tmp_path):
    """The acceptance gate: the span-derived restore-overlap ratio in
    the serve_loop artifact is > 0 (restore lanes genuinely advance
    under resident decode) and agrees with the scheduler counters."""
    from hcache_deepspeed_tpu.inference.benchmark import run_serve_loop
    rows = run_serve_loop(model_size="tiny", n_requests=16, rps=100.0,
                          virtual_clock=True,
                          out=str(tmp_path / "sl.jsonl"))
    summary = rows[-1]
    assert summary["restore_overlap_ratio"] > 0
    span_rs = summary["extra"]["step_breakdown"]["restore"]
    assert span_rs["overlap_ratio"] == pytest.approx(
        summary["restore_overlap_ratio"])
    assert span_rs["overlap_ratio"] > 0
    assert span_rs["chunks_issued"] >= span_rs["scheduler_restores"]


def test_serve_bench_restore_crossover_mode(tmp_path):
    """restore_crossover: one JSONL row per prompt length carrying the
    measured marginal costs AND the analytic model's verdict, plus a
    summary row with the calibrated rates — and the model's choice
    always matches its own cheaper analytic side."""
    from hcache_deepspeed_tpu.inference.benchmark import \
        run_restore_crossover
    out = tmp_path / "crossover.jsonl"
    rows = run_restore_crossover(model_size="tiny", max_context=128,
                                 prompt_lens=(16, 48), chain=2,
                                 out=str(out))
    curve = [r for r in rows if r["phase"] == "restore-crossover"]
    assert [r["prompt_len"] for r in curve] == [16, 48]
    for row in curve:
        assert row["prefill_ms"] >= 0 and row["restore_ms"] >= 0
        assert row["model_choice"] in ("restore", "recompute")
        assert row["measured_winner"] in ("restore", "recompute")
        cheaper = "restore" if row["restore_pred_ms"] <= \
            row["recompute_pred_ms"] else "recompute"
        assert row["model_choice"] == cheaper
    summary = rows[-1]
    assert summary["phase"] == "restore-crossover-summary"
    assert summary["calibration"]["calibrated"]
    assert summary["calibration"]["samples"]["prefill"] >= 2
    import json as _json
    lines = [_json.loads(l) for l in out.read_text().splitlines()]
    assert len(lines) == len(rows)
