"""Prefix caching: full KV blocks shared by refcount across sequences
with identical prompt prefixes (no reference analog — FastGen lacks
prefix caching; this is a beyond-parity feature of the TPU engine)."""

import jax
import numpy as np
import pytest

from hcache_deepspeed_tpu.inference import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig)
from hcache_deepspeed_tpu.models.llama import LlamaForCausalLM, llama_tiny

BS = 16


@pytest.fixture(scope="module")
def tiny():
    cfg = llama_tiny(max_positions=128, use_flash=False)
    model = LlamaForCausalLM(cfg)
    batch = {"input_ids": np.zeros((1, 8), np.int32)}
    params = model.init(jax.random.PRNGKey(0), batch, train=False)["params"]
    return cfg, model, params


def make_engine(cfg, params, prefix_caching=True, blocks=24):
    return InferenceEngineV2(
        cfg, params,
        config=RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 8,
                           "max_ragged_batch_size": 256,
                           "max_ragged_sequence_count": 4,
                           "max_context": 128,
                           "prefix_caching": prefix_caching},
            kv_cache={"block_size": BS, "num_blocks": blocks,
                      "cache_dtype": "float32"},
            hcache={"enable_latents": False}))


def full_logits(model, params, tokens):
    out = model.apply({"params": params},
                      {"input_ids": np.asarray(tokens, np.int32)[None]},
                      train=False, return_logits=True)
    return np.asarray(out)[0]


class TestPrefixCaching:

    def test_latents_incompatible(self, tiny):
        cfg, _, params = tiny
        with pytest.raises(ValueError, match="prefix_caching"):
            InferenceEngineV2(
                cfg, params,
                config=RaggedInferenceEngineConfig(
                    state_manager={"prefix_caching": True},
                    kv_cache={"block_size": BS, "num_blocks": 8},
                    hcache={"enable_latents": True}))

    def test_identical_prompts_share_blocks(self, tiny):
        cfg, model, params = tiny
        engine = make_engine(cfg, params)
        rng = np.random.default_rng(0)
        prompt = list(rng.integers(0, cfg.vocab_size, (3 * BS + 5,)))

        engine.put([1], [prompt])
        free_after_first = engine.state.free_blocks
        logits2, _ = engine.put([2], [prompt])
        # second sequence allocates only the tail block
        assert free_after_first - engine.state.free_blocks == 1
        s1 = engine.state.get_sequence(1)
        s2 = engine.state.get_sequence(2)
        assert s2.blocks[:3] == s1.blocks[:3]      # shared by reference
        assert s2.blocks[3] != s1.blocks[3]
        # logits are exact: same cache content, same math
        ref = full_logits(model, params, prompt)
        np.testing.assert_allclose(logits2[0], ref[-1], atol=2e-2)

        # decode continues correctly on the shared cache
        nxt = int(np.argmax(logits2[0]))
        out, _ = engine.put([2], [[nxt]])
        ref2 = full_logits(model, params, prompt + [nxt])
        np.testing.assert_allclose(out[0], ref2[-1], atol=2e-2)

    def test_flush_order_refcounts(self, tiny):
        cfg, _, params = tiny
        engine = make_engine(cfg, params)
        rng = np.random.default_rng(1)
        prompt = list(rng.integers(0, cfg.vocab_size, (2 * BS + 3,)))
        engine.put([1], [prompt])
        engine.put([2], [prompt])
        shared = engine.state.get_sequence(2).blocks[:2]
        engine.flush(1)            # owner leaves; sharer keeps blocks
        for b in shared:
            assert engine.state.allocator.refcount(b) == 1
        logits, _ = engine.put([2], [[5]])    # sharer still decodes
        assert np.all(np.isfinite(logits))
        engine.flush(2)
        for b in shared:
            assert engine.state.allocator.refcount(b) == 0

    def test_divergent_prompts_share_common_prefix_only(self, tiny):
        cfg, model, params = tiny
        engine = make_engine(cfg, params)
        rng = np.random.default_rng(2)
        common = list(rng.integers(0, cfg.vocab_size, (2 * BS,)))
        a = common + list(rng.integers(0, cfg.vocab_size, (BS,)))
        b = common + list(rng.integers(0, cfg.vocab_size, (BS,)))
        engine.put([1], [a])
        logits, _ = engine.put([2], [b])
        s1, s2 = engine.state.get_sequence(1), engine.state.get_sequence(2)
        assert s2.blocks[:2] == s1.blocks[:2]
        assert s2.blocks[2] != s1.blocks[2]
        ref = full_logits(model, params, b)
        np.testing.assert_allclose(logits[0], ref[-1], atol=2e-2)

    def test_whole_prompt_match_still_runs_one_token(self, tiny):
        cfg, model, params = tiny
        engine = make_engine(cfg, params)
        rng = np.random.default_rng(3)
        prompt = list(rng.integers(0, cfg.vocab_size, (2 * BS,)))
        engine.put([1], [prompt])
        # identical prompt of exactly 2 full blocks: only 1 block may be
        # shared (the last token must produce logits)
        logits, _ = engine.put([2], [prompt])
        s2 = engine.state.get_sequence(2)
        assert s2.blocks[0] == engine.state.get_sequence(1).blocks[0]
        assert s2.blocks[1] != engine.state.get_sequence(1).blocks[1]
        ref = full_logits(model, params, prompt)
        np.testing.assert_allclose(logits[0], ref[-1], atol=2e-2)

    def test_index_purged_after_all_flushed(self, tiny):
        cfg, _, params = tiny
        engine = make_engine(cfg, params)
        rng = np.random.default_rng(4)
        prompt = list(rng.integers(0, cfg.vocab_size, (2 * BS + 1,)))
        engine.put([1], [prompt])
        assert engine._prefix_index
        engine.flush(1)
        assert not engine._prefix_index
        assert not engine._block_prefix

    def test_decode_grown_blocks_become_sharable(self, tiny):
        cfg, model, params = tiny
        engine = make_engine(cfg, params)
        rng = np.random.default_rng(5)
        prompt = list(rng.integers(0, cfg.vocab_size, (BS - 1,)))
        logits, _ = engine.put([1], [prompt])
        toks = list(prompt)
        for _ in range(BS + 2):   # decode past a block boundary
            nxt = int(np.argmax(logits[0]))
            toks.append(nxt)
            logits, _ = engine.put([1], [[nxt]])
        # a new prompt equal to (prompt + generated) shares the full
        # blocks the decode filled
        n_shared_possible = (len(toks) - 1) // BS
        free_before = engine.state.free_blocks
        engine.put([2], [toks])
        used = free_before - engine.state.free_blocks
        assert used == -(-len(toks) // BS) - n_shared_possible
        ref = full_logits(model, params, toks)
        # engine logits for uid 2 come from the shared + fresh cache
        out, _ = engine.put([2], [[int(np.argmax(ref[-1]))]])
        assert np.all(np.isfinite(out))

    def test_in_batch_duplicates_share_via_second_wave(self, tiny):
        cfg, model, params = tiny
        engine = make_engine(cfg, params)
        rng = np.random.default_rng(6)
        prompt = list(rng.integers(0, cfg.vocab_size, (2 * BS + 4,)))
        free0 = engine.state.free_blocks
        logits, _ = engine.put([1, 2], [prompt, prompt])
        # one full set (3 blocks) + one tail block, not 2 full sets
        assert free0 - engine.state.free_blocks == 4
        s1, s2 = engine.state.get_sequence(1), engine.state.get_sequence(2)
        assert s2.blocks[:2] == s1.blocks[:2]
        ref = full_logits(model, params, prompt)
        np.testing.assert_allclose(logits[0], ref[-1], atol=2e-2)
        np.testing.assert_allclose(logits[1], ref[-1], atol=2e-2)
        # both sequences decode independently afterwards
        nxt = int(np.argmax(ref[-1]))
        out, _ = engine.put([1, 2], [[nxt], [nxt]])
        ref2 = full_logits(model, params, prompt + [nxt])
        np.testing.assert_allclose(out[0], ref2[-1], atol=2e-2)
        np.testing.assert_allclose(out[1], ref2[-1], atol=2e-2)

    def test_restored_sequences_never_register(self, tiny):
        """A restore_kv-built sequence has history only for post-restore
        decodes; indexing its blocks under that history would share
        wrong KV (the blocks hold the PROMPT's cache)."""
        cfg, model, params = tiny
        # latents from a capture-enabled twin
        lat_engine = InferenceEngineV2(
            cfg, params,
            config=RaggedInferenceEngineConfig(
                state_manager={"max_tracked_sequences": 8,
                               "max_context": 128},
                kv_cache={"block_size": BS, "num_blocks": 24,
                          "cache_dtype": "float32"}))
        rng = np.random.default_rng(7)
        prompt = list(rng.integers(0, cfg.vocab_size, (2 * BS,)))
        logits, latents = lat_engine.put([1], [prompt])

        engine = make_engine(cfg, params)
        engine.restore_kv([1], [prompt], [latents[0]])
        cur = int(np.argmax(logits[0]))
        for _ in range(BS + 1):   # decode past a block boundary
            out, _ = engine.put([1], [[cur]])
            cur = int(np.argmax(out[0]))
        # nothing registered: history (decodes only) != seen_tokens
        assert not engine._prefix_index

    def test_unindex_survives_deep_chain(self, tiny):
        """A ~64k-token shared prefix at block_size 16 is a 4000-level
        chain; purging it must not hit the Python recursion limit
        (advisor finding: the old recursive walk died at ~1000)."""
        cfg, _, params = tiny
        engine = make_engine(cfg, params)
        depth = 4000          # >> default recursionlimit
        parent = -1
        for i in range(depth):
            key = (parent, i)
            bid = 10_000 + i   # synthetic ids, never touch the allocator
            engine._prefix_index[key] = bid
            engine._block_prefix[bid] = key
            if parent != -1:
                engine._chain_children.setdefault(parent, set()).add(key)
            parent = bid
        engine._unindex_subtree(10_000)
        # everything below the root is gone; the root itself is the
        # caller's (purge loop's) responsibility
        assert len(engine._prefix_index) == 1
        assert len(engine._block_prefix) == 1
        assert not engine._chain_children


@pytest.mark.slow
class TestPrefixCachingFuzz:
    """Randomized interleavings of shared-prefix admissions, decodes,
    flushes and suspend/resume under pool pressure; every decode's
    logits check against a full-context recompute, so refcount bugs,
    stale chain entries after purge, or cross-sequence block corruption
    surface at the exact op that broke them."""

    def test_random_interleavings(self, tiny):
        cfg, model, params = tiny
        engine = make_engine(cfg, params, blocks=30)
        rng = np.random.default_rng(99)
        bases = [list(rng.integers(0, cfg.vocab_size, (2 * BS,)))
                 for _ in range(3)]
        shadows = {}     # uid -> list of tokens whose KV is cached
        suspended = set()
        next_uid = 0

        def check(uid, logits):
            ref = full_logits(model, params, shadows[uid])
            np.testing.assert_allclose(logits, ref[-1], atol=2e-2)

        for _ in range(70):
            op = rng.choice(["new", "new", "decode", "decode", "decode",
                             "flush", "suspend", "resume"])
            live = [u for u in shadows if u not in suspended]
            if op == "new" and len(shadows) < 4:
                base = bases[int(rng.integers(len(bases)))]
                tail = list(rng.integers(0, cfg.vocab_size,
                                         (int(rng.integers(1, 20)),)))
                prompt = base + tail
                from hcache_deepspeed_tpu.inference import SchedulingResult
                if engine.can_schedule([next_uid], [len(prompt)]) != \
                        SchedulingResult.Success:
                    continue
                logits, _ = engine.put([next_uid], [prompt])
                shadows[next_uid] = list(prompt)
                check(next_uid, logits[0])
                next_uid += 1
            elif op == "decode" and live:
                uid = int(rng.choice(live))
                if len(shadows[uid]) + 1 > 128:
                    continue
                tok = int(rng.integers(0, cfg.vocab_size))
                shadows[uid].append(tok)
                logits, _ = engine.put([uid], [[tok]])
                check(uid, logits[0])
            elif op == "flush" and shadows:
                uid = int(rng.choice(list(shadows)))
                engine.flush(uid)
                del shadows[uid]
                suspended.discard(uid)
            elif op == "suspend" and live:
                uid = int(rng.choice(live))
                engine.suspend_sequence(uid)
                suspended.add(uid)
            elif op == "resume" and suspended:
                from hcache_deepspeed_tpu.inference import SchedulingError
                uid = int(rng.choice(list(suspended)))
                try:
                    engine.resume_sequence(uid)
                except SchedulingError:
                    continue    # pool too full right now — legal
                suspended.remove(uid)

        # teardown invariant: freeing everything empties the index
        for uid in list(shadows):
            engine.flush(uid)
        assert not engine._prefix_index
        assert not engine._block_prefix
        assert all(not v for v in engine._chain_children.values())
