"""Sequence KV host offload (reference: BlockedKVCache's optional
host-offloaded blocks) — exact suspend/resume, vs HCache restore's
recompute-from-latents."""

import jax
import numpy as np
import pytest

from hcache_deepspeed_tpu.inference import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig,
                                            SchedulingError)
from hcache_deepspeed_tpu.models.llama import LlamaForCausalLM, llama_tiny


@pytest.fixture(scope="module")
def tiny():
    cfg = llama_tiny(max_positions=128, use_flash=False)
    model = LlamaForCausalLM(cfg)
    batch = {"input_ids": np.zeros((1, 8), np.int32)}
    params = model.init(jax.random.PRNGKey(0), batch, train=False)["params"]
    return cfg, params


def make_engine(cfg, params, num_blocks=12):
    return InferenceEngineV2(
        cfg, params,
        config=RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 8,
                           "max_context": 128},
            kv_cache={"block_size": 16, "num_blocks": num_blocks,
                      "cache_dtype": "float32"}))


def test_suspend_frees_blocks_resume_continues_exactly(tiny):
    cfg, params = tiny
    rng = np.random.default_rng(0)
    prompt = list(rng.integers(0, cfg.vocab_size, (20,)))

    ref = make_engine(cfg, params)
    lr, _ = ref.put([1], [prompt])
    tok = int(np.argmax(lr[0]))
    ref_dec, _ = ref.put([1], [[tok]])

    eng = make_engine(cfg, params)
    le, _ = eng.put([1], [prompt])
    free_before = eng.state.free_blocks
    eng.suspend_sequence(1)
    assert eng.state.free_blocks > free_before        # blocks released
    with pytest.raises(RuntimeError, match="suspended"):
        eng.put([1], [[tok]])
    eng.resume_sequence(1)
    dec, _ = eng.put([1], [[tok]])
    np.testing.assert_allclose(np.asarray(dec[0]),
                               np.asarray(ref_dec[0]), atol=1e-5)


def test_suspended_blocks_reusable_by_others(tiny):
    cfg, params = tiny
    rng = np.random.default_rng(1)
    # pool of 12 blocks (1 scratch): two 80-token sequences (5 blocks
    # each) cannot coexist with a third — suspend makes room
    s1 = list(rng.integers(0, cfg.vocab_size, (80,)))
    s2 = list(rng.integers(0, cfg.vocab_size, (80,)))
    eng = make_engine(cfg, params, num_blocks=12)
    l1, _ = eng.put([1], [s1])
    eng.suspend_sequence(1)
    l2, _ = eng.put([2], [s2])      # fits only because 1 is suspended
    eng.flush(2)
    eng.resume_sequence(1)
    tok = int(np.argmax(l1[0]))
    dec, _ = eng.put([1], [[tok]])
    ref = make_engine(cfg, params)
    ref.put([1], [s1])
    ref_dec, _ = ref.put([1], [[tok]])
    np.testing.assert_allclose(np.asarray(dec[0]),
                               np.asarray(ref_dec[0]), atol=1e-5)


def test_resume_without_room_raises(tiny):
    cfg, params = tiny
    rng = np.random.default_rng(2)
    # 8 blocks (1 scratch -> 7 usable): each 80-token seq needs 5
    eng = make_engine(cfg, params, num_blocks=8)
    s1 = list(rng.integers(0, cfg.vocab_size, (80,)))
    s2 = list(rng.integers(0, cfg.vocab_size, (80,)))
    eng.put([1], [s1])
    eng.suspend_sequence(1)
    eng.put([2], [s2])              # occupies the freed blocks
    with pytest.raises(SchedulingError):
        eng.resume_sequence(1)
    eng.flush(2)
    eng.resume_sequence(1)          # room again


def test_idempotent_and_empty(tiny):
    cfg, params = tiny
    eng = make_engine(cfg, params)
    eng.put([1], [[1, 2, 3]])
    eng.suspend_sequence(1)
    eng.suspend_sequence(1)         # no-op
    eng.resume_sequence(1)
    eng.resume_sequence(1)          # no-op
    with pytest.raises(KeyError):
        eng.suspend_sequence(99)
    # zero-token sequence: suspend/resume is a no-op, not a crash
    eng.state.get_or_create_sequence(5)
    eng.suspend_sequence(5)
    eng.resume_sequence(5)
    logits, _ = eng.put([5], [[7, 8]])
    assert np.isfinite(np.asarray(logits)).all()


def test_restore_kv_rejects_suspended(tiny):
    cfg, params = tiny
    eng = make_engine(cfg, params)
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]
    _, latents = eng.put([1], [prompt])
    eng.suspend_sequence(1)
    with pytest.raises(RuntimeError, match="suspended"):
        eng.restore_kv([1], [prompt], [latents[0]])
