"""Weight-only quantized serving (reference:
``deepspeed/inference/quantization`` — v1 int8 QuantLinear / MoQ
checkpoints)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hcache_deepspeed_tpu.inference import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig)
from hcache_deepspeed_tpu.models.gpt2 import GPT2LMHeadModel, gpt2_tiny
from hcache_deepspeed_tpu.models.llama import LlamaForCausalLM, llama_tiny
from hcache_deepspeed_tpu.ops.quantizer import (QuantizedTensor,
                                                dequantize_tree,
                                                quantize_tree)


def _engine(cfg, params, quantized, fused=False):
    kw = dict(state_manager={"max_tracked_sequences": 4,
                             "max_context": 128},
              kv_cache={"block_size": 16, "num_blocks": 24,
                        "cache_dtype": "float32"})
    if quantized:
        kw["quantization"] = {"enabled": True, "bits": 8,
                              "group_size": 64, "min_size": 1024,
                              "use_fused_kernel": fused}
    return InferenceEngineV2(cfg, params,
                             config=RaggedInferenceEngineConfig(**kw))


class TestQuantizeTree:
    def test_roundtrip_error_bounded(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((64, 64)), jnp.float32)
        qt = QuantizedTensor.make(x, group_size=32)
        err = np.abs(np.asarray(qt.dequantize()) - np.asarray(x)).max()
        # symmetric int8: err <= scale/2 = absmax/254 per group
        assert err < np.abs(np.asarray(x)).max() / 100

    def test_small_and_1d_leaves_skipped(self):
        tree = {"big": jnp.ones((64, 64)), "bias": jnp.ones((64,)),
                "tiny": jnp.ones((4, 4))}
        out = quantize_tree(tree, min_size=1024)
        assert isinstance(out["big"], QuantizedTensor)
        assert not isinstance(out["bias"], QuantizedTensor)
        assert not isinstance(out["tiny"], QuantizedTensor)
        back = dequantize_tree(out)
        assert back["big"].shape == (64, 64)

    def test_quantized_tensor_jits(self):
        x = jnp.asarray(np.random.default_rng(1).standard_normal((64, 64)),
                        jnp.float32)
        qt = QuantizedTensor.make(x, group_size=32)

        @jax.jit
        def f(t):
            return dequantize_tree({"w": t})["w"].sum()

        assert np.isfinite(float(f(qt)))


@pytest.mark.parametrize(
    "family", ["llama", "gpt2", "opt", "falcon", "phi", "mixtral"])
class TestQuantizedServing:
    def _setup(self, family):
        if family == "llama":
            cfg = llama_tiny(hidden_size=128, intermediate_size=256,
                             max_positions=128, use_flash=False)
            model = LlamaForCausalLM(cfg)
        elif family == "gpt2":
            cfg = gpt2_tiny(n_embd=128, n_positions=128, use_flash=False)
            model = GPT2LMHeadModel(cfg)
        elif family == "opt":
            from hcache_deepspeed_tpu.models.opt import (OPTForCausalLM,
                                                         opt_tiny)
            cfg = opt_tiny(hidden_size=128, ffn_dim=256, use_flash=False)
            model = OPTForCausalLM(cfg)
        elif family == "falcon":
            from hcache_deepspeed_tpu.models.falcon import (
                FalconForCausalLM, falcon_tiny)
            cfg = falcon_tiny(hidden_size=128, n_head=4, use_flash=False)
            model = FalconForCausalLM(cfg)
        elif family == "phi":
            from hcache_deepspeed_tpu.models.phi import (PhiForCausalLM,
                                                         phi_tiny)
            cfg = phi_tiny(hidden_size=128, intermediate_size=256,
                           use_flash=False)
            model = PhiForCausalLM(cfg)
        else:
            from hcache_deepspeed_tpu.models.mixtral import (
                MixtralForCausalLM, mixtral_tiny)
            cfg = mixtral_tiny(hidden_size=128, intermediate_size=256,
                               max_positions=128, use_flash=False,
                               dropless=True)
            model = MixtralForCausalLM(cfg)
        batch = {"input_ids": np.zeros((1, 8), np.int32)}
        params = model.init(jax.random.PRNGKey(0), batch,
                            train=False)["params"]
        return cfg, params

    def test_moe_router_stays_fp32(self, family):
        if family != "mixtral":
            pytest.skip("router check is MoE-only")
        cfg, params = self._setup(family)
        engine = _engine(cfg, params, quantized=True)
        wg = engine.model.params["layers"]["mlp"]["moe"]["wg"]
        assert not isinstance(wg, QuantizedTensor)
        assert wg.dtype == jnp.float32

    def test_weights_stored_int8(self, family):
        # trunk kernels land in the k-major MatmulQuantizedTensor
        # layout (both int8 modes); embed/head in the flat
        # QuantizedTensor layout — all storage must be int8
        from hcache_deepspeed_tpu.ops.quantized_matmul import \
            MatmulQuantizedTensor
        cfg, params = self._setup(family)
        engine = _engine(cfg, params, quantized=True)
        containers = (QuantizedTensor, MatmulQuantizedTensor)
        leaves = jax.tree.leaves(
            engine.model.params,
            is_leaf=lambda x: isinstance(x, containers))
        quantized = [l for l in leaves if isinstance(l, containers)]
        assert len(quantized) > 0
        assert any(isinstance(l, MatmulQuantizedTensor)
                   for l in quantized)   # the trunk layout
        for l in quantized:
            assert l.q.dtype == jnp.int8

    def test_logits_close_to_fp(self, family):
        cfg, params = self._setup(family)
        rng = np.random.default_rng(3)
        prompt = list(rng.integers(0, cfg.vocab_size, (12,)))
        fp = _engine(cfg, params, quantized=False)
        q8 = _engine(cfg, params, quantized=True)
        lf, _ = fp.put([1], [prompt])
        lq, _ = q8.put([1], [prompt])
        lf, lq = np.asarray(lf[0]), np.asarray(lq[0])
        # int8 weights: logits agree to a coarse tolerance; a random
        # tiny model has near-tie logits, so instead of exact-argmax we
        # require the fp winner to be within quantization noise of the
        # quantized maximum
        scale = np.abs(lf).max() + 1e-6
        assert np.abs(lf - lq).max() / scale < 0.15
        assert lq[np.argmax(lf)] >= lq.max() - 0.1 * scale

    def test_fused_kernel_mode_close_to_fp(self, family):
        """use_fused_kernel routes layer matmuls through the int8-weight
        kernel (its k-groups differ from the dequant path's flat groups,
        so the comparison target is the fp baseline, same tolerance as
        the dequant mode); every trunk supports it."""
        cfg, params = self._setup(family)
        rng = np.random.default_rng(5)
        prompt = list(rng.integers(0, cfg.vocab_size, (10,)))
        fp = _engine(cfg, params, quantized=False)
        qf = _engine(cfg, params, quantized=True, fused=True)
        from hcache_deepspeed_tpu.ops.quantized_matmul import \
            MatmulQuantizedTensor
        leaves = jax.tree.leaves(
            qf.model.params,
            is_leaf=lambda x: isinstance(x, MatmulQuantizedTensor))
        assert any(isinstance(l, MatmulQuantizedTensor) for l in leaves)
        lfp, _ = fp.put([1], [prompt])
        lf, _ = qf.put([1], [prompt])
        lfp, lf = np.asarray(lfp[0]), np.asarray(lf[0])
        scale = np.abs(lfp).max() + 1e-6
        assert np.abs(lfp - lf).max() / scale < 0.15
        # restore works through the fused weights too
        qf2 = _engine(cfg, params, quantized=True, fused=True)
        _, latents = qf.put([2], [prompt])
        qf2.restore_kv([2], [prompt], [latents[0]])
        nxt = int(np.argmax(lf))
        da, _ = qf.put([2], [[nxt]])
        db, _ = qf2.put([2], [[nxt]])
        np.testing.assert_allclose(np.asarray(db[0]), np.asarray(da[0]),
                                   atol=2e-2)

    def test_restore_kv_with_quantized_weights(self, family):
        cfg, params = self._setup(family)
        rng = np.random.default_rng(4)
        prompt = list(rng.integers(0, cfg.vocab_size, (9,)))
        a = _engine(cfg, params, quantized=True)
        la, latents = a.put([1], [prompt])
        nxt = int(np.argmax(la[0]))
        dec_a, _ = a.put([1], [[nxt]])
        b = _engine(cfg, params, quantized=True)
        b.restore_kv([1], [prompt], [latents[0]])
        dec_b, _ = b.put([1], [[nxt]])
        np.testing.assert_allclose(np.asarray(dec_b[0]),
                                   np.asarray(dec_a[0]), atol=2e-2)


class TestInt4Serving:
    """bits=4 rides the same weight-only path (reference: the int4
    groupwise quantizer, csrc/quantization quantize_intX)."""

    def _setup(self):
        from hcache_deepspeed_tpu.models.llama import (LlamaForCausalLM,
                                                       llama_tiny)
        cfg = llama_tiny(max_positions=128, use_flash=False)
        model = LlamaForCausalLM(cfg)
        params = model.init(jax.random.PRNGKey(0),
                            {"input_ids": np.zeros((1, 8), np.int32)},
                            train=False)["params"]
        return cfg, params

    def test_int4_serves_with_bounded_drift(self):
        cfg, params = self._setup()
        kw = dict(state_manager={"max_tracked_sequences": 4,
                                 "max_context": 128},
                  kv_cache={"block_size": 16, "num_blocks": 24,
                            "cache_dtype": "float32"})
        fp = InferenceEngineV2(cfg, params,
                               config=RaggedInferenceEngineConfig(**kw))
        q4 = InferenceEngineV2(
            cfg, params,
            config=RaggedInferenceEngineConfig(
                **kw, quantization={"enabled": True, "bits": 4,
                                    "group_size": 32, "min_size": 1024}))
        rng = np.random.default_rng(5)
        prompt = list(rng.integers(0, cfg.vocab_size, (10,)))
        lf, _ = fp.put([1], [prompt])
        lq, _ = q4.put([1], [prompt])
        lf, lq = np.asarray(lf[0]), np.asarray(lq[0])
        assert np.isfinite(lq).all()
        scale = np.abs(lf).max() + 1e-6
        # int4 is coarser than int8: wider but still bounded drift
        assert np.abs(lf - lq).max() / scale < 0.45


def test_group_misaligned_trunk_leaf_stays_dense():
    """A trunk leaf whose K is not a group multiple must stay FULL
    precision — not fall through to the flat QuantizedTensor layout,
    whose dequant path is slower than dense at decode (81 vs 18
    ms/token measured at 7B). Serving must still work."""
    from hcache_deepspeed_tpu.ops.quantized_matmul import \
        MatmulQuantizedTensor
    cfg = llama_tiny(hidden_size=128, intermediate_size=160,
                     max_positions=128, use_flash=False)
    model = LlamaForCausalLM(cfg)
    batch = {"input_ids": np.zeros((1, 8), np.int32)}
    params = model.init(jax.random.PRNGKey(0), batch,
                        train=False)["params"]
    engine = _engine(cfg, params, quantized=True)   # group 64
    containers = (QuantizedTensor, MatmulQuantizedTensor)
    flat = jax.tree_util.tree_flatten_with_path(
        engine.model.params,
        is_leaf=lambda x: isinstance(x, containers))[0]
    down = [(p, l) for p, l in flat
            if "down" in "/".join(str(getattr(k, "key", k)) for k in p)]
    assert down, "down-proj leaf not found"
    for _, leaf in down:   # K=160 % 64 != 0 -> dense
        assert not isinstance(leaf, containers)
        assert jnp.issubdtype(leaf.dtype, jnp.floating)
    assert any(isinstance(l, MatmulQuantizedTensor)
               for _, l in flat)   # aligned trunk still quantized
    out = engine.generate([list(range(10))], max_new_tokens=4)
    assert len(out[0]) == 4


def test_untied_head_quantizes_k_major():
    """The untied LM head must land in the k-major MatmulQuantizedTensor
    layout at tp==1 (the flat layout dequantizes the WHOLE head every
    decode step — ~0.4 GB of bf16 materialized per token at 7B) and
    still produce close-to-fp logits through _head_logits/_mm."""
    from hcache_deepspeed_tpu.ops.quantized_matmul import \
        MatmulQuantizedTensor
    cfg = llama_tiny(hidden_size=128, intermediate_size=256,
                     max_positions=128, use_flash=False)
    assert not cfg.tie_word_embeddings
    model = LlamaForCausalLM(cfg)
    batch = {"input_ids": np.zeros((1, 8), np.int32)}
    params = model.init(jax.random.PRNGKey(0), batch,
                        train=False)["params"]
    q8 = _engine(cfg, params, quantized=True, fused=True)
    assert isinstance(q8.model.params["lm_head"], MatmulQuantizedTensor)
    fp = _engine(cfg, params, quantized=False)
    rng = np.random.default_rng(7)
    prompt = list(rng.integers(0, cfg.vocab_size, (12,)))
    lf, _ = fp.put([1], [prompt])
    lq, _ = q8.put([1], [prompt])
    lf, lq = np.asarray(lf[0]), np.asarray(lq[0])
    scale = np.abs(lf).max() + 1e-6
    assert np.abs(lf - lq).max() / scale < 0.15


def test_untied_head_misaligned_group_recorded_not_silently_dense():
    """An untied LM head whose K (= hidden) is not a group multiple
    must be RECORDED in the quantization skip list — staying full
    precision with the same warning the trunk path gets — instead of
    silently falling through (and then being re-quantized by the flat
    dequant-on-use fallback, which is slower than dense at decode)."""
    from hcache_deepspeed_tpu.ops.quantized_matmul import \
        MatmulQuantizedTensor
    # hidden 96 % group 64 != 0: head (and trunk) misaligned
    cfg = llama_tiny(hidden_size=96, intermediate_size=128,
                     max_positions=128, use_flash=False)
    assert not cfg.tie_word_embeddings
    model = LlamaForCausalLM(cfg)
    batch = {"input_ids": np.zeros((1, 8), np.int32)}
    params = model.init(jax.random.PRNGKey(0), batch,
                        train=False)["params"]
    q8 = _engine(cfg, params, quantized=True, fused=True)
    head = q8.model.params["lm_head"]
    assert not isinstance(head, (QuantizedTensor,
                                 MatmulQuantizedTensor))
    assert jnp.issubdtype(head.dtype, jnp.floating)
    # aligned hidden on the same vocab still quantizes k-major — the
    # new skip is the misalignment record, not a blanket head opt-out
    cfg2 = llama_tiny(hidden_size=128, intermediate_size=256,
                      max_positions=128, use_flash=False)
    model2 = LlamaForCausalLM(cfg2)
    params2 = model2.init(jax.random.PRNGKey(0), batch,
                          train=False)["params"]
    q82 = _engine(cfg2, params2, quantized=True, fused=True)
    assert isinstance(q82.model.params["lm_head"],
                      MatmulQuantizedTensor)
    # and the misaligned engine still serves
    out = q8.generate([list(range(10))], max_new_tokens=3)
    assert len(out[0]) == 3
