"""Inference v2 engine tests.

Reference analog: ``tests/unit/inference/v2/`` (module/kernel/e2e tests).
The reference has NO tests for the fork's ``restore_kv`` (SURVEY.md §4) —
the restore tests here are new coverage.
"""

import jax
import numpy as np
import pytest

from hcache_deepspeed_tpu.inference import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig,
                                            SchedulingError,
                                            SchedulingResult, build_hf_engine)
from hcache_deepspeed_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                               llama_tiny)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = llama_tiny(max_positions=128, use_flash=False)
    model = LlamaForCausalLM(cfg)
    batch = {"input_ids": np.zeros((1, 8), np.int32)}
    params = model.init(jax.random.PRNGKey(0), batch, train=False)["params"]
    return cfg, model, params


def make_engine(cfg, params, **over):
    kw = dict(state_manager={"max_tracked_sequences": 8,
                             "max_ragged_batch_size": 128,
                             "max_ragged_sequence_count": 4,
                             "max_context": 128},
              kv_cache={"block_size": 16, "num_blocks": 24,
                        "cache_dtype": "float32"})
    kw.update(over)
    return InferenceEngineV2(cfg, params,
                             config=RaggedInferenceEngineConfig(**kw))


def full_logits(model, params, tokens):
    """Reference: full-context forward through the *training* model."""
    out = model.apply({"params": params},
                      {"input_ids": np.asarray(tokens, np.int32)[None]},
                      train=False, return_logits=True)
    return np.asarray(out)[0]


class TestPrefillDecode:

    def test_prefill_matches_full_forward(self, tiny_model):
        cfg, model, params = tiny_model
        engine = make_engine(cfg, params)
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, cfg.vocab_size, (13,))
        logits, latents = engine.put([7], [tokens])
        ref = full_logits(model, params, tokens)
        np.testing.assert_allclose(logits[0], ref[-1], atol=2e-2)
        # latents: [L, T, H] per sequence
        assert latents[0].shape == (cfg.n_layer, 13, cfg.hidden_size)

    def test_incremental_decode_matches_full_forward(self, tiny_model):
        cfg, model, params = tiny_model
        engine = make_engine(cfg, params)
        rng = np.random.default_rng(1)
        tokens = list(rng.integers(0, cfg.vocab_size, (9,)))
        engine.put([1], [tokens])
        for step in range(5):
            nxt = int(rng.integers(0, cfg.vocab_size))
            tokens.append(nxt)
            logits, _ = engine.put([1], [[nxt]])
            ref = full_logits(model, params, tokens)
            np.testing.assert_allclose(logits[0], ref[-1], atol=2e-2)

    def test_ragged_batch_mixed(self, tiny_model):
        """Two decoding sequences + one fresh prefill in one put()."""
        cfg, model, params = tiny_model
        engine = make_engine(cfg, params)
        rng = np.random.default_rng(2)
        s1 = list(rng.integers(0, cfg.vocab_size, (7,)))
        s2 = list(rng.integers(0, cfg.vocab_size, (12,)))
        engine.put([1, 2], [s1, s2])
        s3 = list(rng.integers(0, cfg.vocab_size, (5,)))
        n1, n2 = int(rng.integers(256)), int(rng.integers(256))
        logits, latents = engine.put([1, 2, 3], [[n1], [n2], s3])
        s1.append(n1)
        s2.append(n2)
        np.testing.assert_allclose(logits[0],
                                   full_logits(model, params, s1)[-1],
                                   atol=2e-2)
        np.testing.assert_allclose(logits[1],
                                   full_logits(model, params, s2)[-1],
                                   atol=2e-2)
        np.testing.assert_allclose(logits[2],
                                   full_logits(model, params, s3)[-1],
                                   atol=2e-2)
        assert latents[0].shape[1] == 1 and latents[2].shape[1] == 5

    def test_greedy_generation_consistency(self, tiny_model):
        """Greedy engine generation == greedy full-recompute generation."""
        cfg, model, params = tiny_model
        engine = make_engine(cfg, params)
        rng = np.random.default_rng(3)
        prompt = list(rng.integers(0, cfg.vocab_size, (6,)))
        logits, _ = engine.put([42], [prompt])
        engine_seq = list(prompt)
        for _ in range(8):
            nxt = int(np.argmax(logits[0]))
            engine_seq.append(nxt)
            logits, _ = engine.put([42], [[nxt]])

        ref_seq = list(prompt)
        for _ in range(8):
            ref = full_logits(model, params, ref_seq)
            ref_seq.append(int(np.argmax(ref[-1])))
        assert engine_seq == ref_seq


class TestHCacheRestore:
    """The fork's flagship: restore_kv rebuilds KV from latents."""

    def test_restore_equals_recompute(self, tiny_model):
        cfg, model, params = tiny_model
        rng = np.random.default_rng(4)
        prompt = list(rng.integers(0, cfg.vocab_size, (11,)))

        # path A: prefill, keep cache, decode
        engine_a = make_engine(cfg, params)
        logits_a, latents = engine_a.put([1], [prompt])
        nxt = int(np.argmax(logits_a[0]))
        dec_a, _ = engine_a.put([1], [[nxt]])

        # path B: restore from latents (no prefill forward), then decode
        engine_b = make_engine(cfg, params)
        engine_b.restore_kv([1], [prompt], [latents[0]])
        seq = engine_b.state.get_sequence(1)
        assert seq.seen_tokens == len(prompt)
        dec_b, _ = engine_b.put([1], [[nxt]])

        np.testing.assert_allclose(dec_b[0], dec_a[0], atol=2e-2)

    def test_restore_then_long_generation(self, tiny_model):
        cfg, model, params = tiny_model
        rng = np.random.default_rng(5)
        prompt = list(rng.integers(0, cfg.vocab_size, (9,)))

        engine = make_engine(cfg, params)
        logits, latents = engine.put([1], [prompt])
        engine.flush(1)
        assert engine.state.get_sequence(1) is None

        engine.restore_kv([1], [prompt], [latents[0]])
        seq = list(prompt)
        cur = int(np.argmax(logits[0]))
        for _ in range(6):
            seq.append(cur)
            out, _ = engine.put([1], [[cur]])
            ref = full_logits(model, params, seq)
            np.testing.assert_allclose(out[0], ref[-1], atol=2e-2)
            cur = int(np.argmax(out[0]))

    def test_latents_disabled(self, tiny_model):
        cfg, _, params = tiny_model
        engine = make_engine(cfg, params, hcache={"enable_latents": False})
        logits, latents = engine.put([1], [[1, 2, 3]])
        assert latents[0] is None or latents[0].shape[-1] == 0


class TestScheduling:

    def test_sequence_limit(self, tiny_model):
        cfg, _, params = tiny_model
        engine = make_engine(cfg, params)
        res = engine.can_schedule(list(range(9)), [1] * 9)
        assert res == SchedulingResult.EngineSequenceLimitExceeded
        res = engine.can_schedule(list(range(5)), [1] * 5)
        assert res == SchedulingResult.BatchSequenceLimitExceeded

    def test_token_limit(self, tiny_model):
        cfg, _, params = tiny_model
        engine = make_engine(cfg, params)
        assert engine.can_schedule([1, 2], [100, 100]) == \
            SchedulingResult.BatchTokenLimitExceeded

    def test_seq_len_limit(self, tiny_model):
        cfg, _, params = tiny_model
        engine = make_engine(cfg, params)
        assert engine.can_schedule([1], [300]) == \
            SchedulingResult.BatchTokenLimitExceeded
        # within batch budget but beyond per-seq context
        engine2 = make_engine(cfg, params,
                              state_manager={"max_ragged_batch_size": 1024,
                                             "max_context": 64})
        assert engine2.can_schedule([1], [100]) == \
            SchedulingResult.SequenceTokenLimitExceeded

    def test_kv_limit_and_error(self, tiny_model):
        cfg, _, params = tiny_model
        engine = make_engine(cfg, params,
                             kv_cache={"block_size": 16, "num_blocks": 3,
                                       "cache_dtype": "float32"})
        # 2 usable blocks (1 reserved scratch) = 32 tokens
        assert engine.can_schedule([1], [64]) == \
            SchedulingResult.KVCacheLimitExceeded
        with pytest.raises(SchedulingError):
            engine.put([1], [list(range(64))])

    def test_query_budget(self, tiny_model):
        cfg, _, params = tiny_model
        engine = make_engine(cfg, params)
        tokens, blocks = engine.query(5, 1000, 1000)
        assert tokens == 128  # max_context cap
        assert blocks == 128 // 16
        engine.put([5], [[1, 2, 3]])
        tokens2, blocks2 = engine.query(5, 1000, 1000)
        assert tokens2 == 125
        assert blocks2 == 8 - 1  # one block already held

    def test_flush_frees_blocks(self, tiny_model):
        cfg, _, params = tiny_model
        engine = make_engine(cfg, params)
        free0 = engine.state.free_blocks
        engine.put([1], [list(range(40))])
        assert engine.state.free_blocks < free0
        engine.flush(1)
        assert engine.state.free_blocks == free0


class TestFactory:

    def test_build_hf_engine(self, tiny_model):
        cfg, _, params = tiny_model
        hf = {"model_type": "llama", "vocab_size": cfg.vocab_size,
              "hidden_size": cfg.hidden_size,
              "intermediate_size": cfg.intermediate_size,
              "num_hidden_layers": cfg.n_layer,
              "num_attention_heads": cfg.n_head,
              "num_key_value_heads": cfg.n_kv_head,
              "max_position_embeddings": cfg.max_positions,
              "torch_dtype": "float32"}
        engine = build_hf_engine(
            hf, params,
            engine_config=RaggedInferenceEngineConfig(
                kv_cache={"block_size": 16, "num_blocks": 16,
                          "cache_dtype": "float32"},
                state_manager={"max_context": 128}))
        logits, _ = engine.put([1], [[1, 2, 3]])
        assert logits.shape == (1, cfg.vocab_size)

    def test_unknown_family(self, tiny_model):
        cfg, _, params = tiny_model
        with pytest.raises(ValueError, match="unsupported model family"):
            build_hf_engine({"model_type": "rwkv"}, params)


class TestGenerateFused:
    """On-device decode loop vs the host-driven paths."""

    def test_fused_matches_stepwise_greedy(self, tiny_model):
        cfg, model, params = tiny_model
        rng = np.random.default_rng(6)
        prompts = [list(rng.integers(0, cfg.vocab_size, (n,)))
                   for n in (5, 9, 3)]

        engine = make_engine(cfg, params,
                             hcache={"enable_latents": False})
        outs, latents = engine.generate_fused(prompts, max_new_tokens=7)
        assert latents == [None] * 3
        assert all(engine.state.get_sequence(u) is None for u in range(3))

        # oracle: greedy continuation through the training model
        for prompt, out in zip(prompts, outs):
            seq = list(prompt)
            for tok in out:
                ref = full_logits(model, params, seq)
                assert tok == int(np.argmax(ref[-1]))
                seq.append(tok)

    def test_fused_single_token(self, tiny_model):
        cfg, model, params = tiny_model
        engine = make_engine(cfg, params)
        prompt = [3, 1, 4, 1, 5]
        outs, _ = engine.generate_fused([prompt], max_new_tokens=1)
        ref = full_logits(model, params, prompt)
        assert outs == [[int(np.argmax(ref[-1]))]]

    def test_fused_rejects_nonpositive_max_new_tokens(self, tiny_model):
        cfg, model, params = tiny_model
        engine = make_engine(cfg, params)
        for bad in (0, -3):
            with pytest.raises(ValueError, match="max_new_tokens"):
                engine.generate_fused([[1, 2, 3]], max_new_tokens=bad)

    def test_fused_eos_truncation(self, tiny_model):
        cfg, model, params = tiny_model
        engine = make_engine(cfg, params)
        rng = np.random.default_rng(7)
        prompt = list(rng.integers(0, cfg.vocab_size, (4,)))
        full, _ = engine.generate_fused([prompt], max_new_tokens=6)
        eos = full[0][2]
        cut, lat = engine.generate_fused([prompt], max_new_tokens=6,
                                         eos_token_id=eos)
        assert cut[0] == full[0][:full[0].index(eos) + 1]
        # the restore contract survives truncation: latents cover
        # prompt + fed tokens only
        assert lat[0].shape[1] == len(prompt) + len(cut[0]) - 1

    def test_fused_does_not_disturb_live_sequences(self, tiny_model):
        """uids must not collide with sequences the caller is serving."""
        cfg, model, params = tiny_model
        engine = make_engine(cfg, params)
        prompt0 = [5, 6, 7]
        engine.put([0], [prompt0])              # live sequence at uid 0
        engine.generate_fused([[9, 8]], max_new_tokens=3)
        seq = engine.state.get_sequence(0)
        assert seq is not None and seq.seen_tokens == 3
        out, _ = engine.put([0], [[2]])         # still decodes correctly
        ref = full_logits(model, params, prompt0 + [2])
        np.testing.assert_allclose(out[0], ref[-1], atol=2e-2)

    def test_fused_latents_restore(self, tiny_model):
        """HCache composition: latents returned by the fused loop restore
        a flushed sequence to the exact decode state."""
        cfg, model, params = tiny_model
        rng = np.random.default_rng(8)
        prompt = list(rng.integers(0, cfg.vocab_size, (8,)))

        engine = make_engine(cfg, params)
        outs, latents = engine.generate_fused([prompt], max_new_tokens=5)
        # latents cover prompt + the 4 fed tokens
        assert latents[0].shape[1] == len(prompt) + 4

        cached_tokens = prompt + outs[0][:-1]
        engine.restore_kv([9], [cached_tokens], [latents[0]])
        out, _ = engine.put([9], [[outs[0][-1]]])
        ref = full_logits(model, params, cached_tokens + [outs[0][-1]])
        np.testing.assert_allclose(out[0], ref[-1], atol=2e-2)


class TestRestoreChunking:
    """Chunked restore dispatches must be invisible to results."""

    @pytest.mark.parametrize("chunk", [1, 2, 0])   # per-layer, mid, auto
    def test_chunk_sizes_agree(self, tiny_model, chunk):
        cfg, model, params = tiny_model
        rng = np.random.default_rng(11)
        prompt = list(rng.integers(0, cfg.vocab_size, (10,)))

        engine_a = make_engine(cfg, params)
        logits_a, latents = engine_a.put([1], [prompt])
        nxt = int(np.argmax(logits_a[0]))
        dec_a, _ = engine_a.put([1], [[nxt]])

        engine_b = make_engine(
            cfg, params, hcache={"enable_latents": True,
                                 "restore_chunk_layers": chunk})
        engine_b.restore_kv([1], [prompt], [latents[0]])
        dec_b, _ = engine_b.put([1], [[nxt]])
        np.testing.assert_allclose(dec_b[0], dec_a[0], atol=2e-2)

    def test_batched_restore_mixed_lengths(self, tiny_model):
        """Several uids restore in one call (grouped by bucket) with
        per-sequence parity against the uninterrupted caches."""
        cfg, model, params = tiny_model
        rng = np.random.default_rng(12)
        prompts = [list(rng.integers(0, cfg.vocab_size, (n,)))
                   for n in (6, 7, 19)]   # two share a bucket, one not
        engine_a = make_engine(cfg, params)
        logits_a, latents = engine_a.put([0, 1, 2], prompts)
        nxt = [int(np.argmax(l)) for l in logits_a]
        dec_a, _ = engine_a.put([0, 1, 2], [[t] for t in nxt])

        engine_b = make_engine(cfg, params)
        engine_b.restore_kv([0, 1, 2], prompts, latents)
        for u, p in zip([0, 1, 2], prompts):
            assert engine_b.state.get_sequence(u).seen_tokens == len(p)
        dec_b, _ = engine_b.put([0, 1, 2], [[t] for t in nxt])
        np.testing.assert_allclose(dec_b, dec_a, atol=2e-2)

    def test_fp8_latents_restore(self, tiny_model):
        """float8 latent capture: half the host-link bytes, restore
        parity within quantization tolerance."""
        cfg, model, params = tiny_model
        rng = np.random.default_rng(13)
        prompt = list(rng.integers(0, cfg.vocab_size, (9,)))

        engine_a = make_engine(cfg, params)
        logits_a, _ = engine_a.put([1], [prompt])
        nxt = int(np.argmax(logits_a[0]))
        dec_a, _ = engine_a.put([1], [[nxt]])

        engine_b = make_engine(
            cfg, params,
            hcache={"enable_latents": True,
                    "latent_dtype": "float8_e4m3fn"})
        _, latents = engine_b.put([1], [prompt])
        import ml_dtypes
        assert latents[0].dtype == ml_dtypes.float8_e4m3fn
        assert latents[0].nbytes == np.prod(latents[0].shape)  # 1 B/elt
        engine_b.flush(1)
        engine_b.restore_kv([1], [prompt], [latents[0]])
        dec_b, _ = engine_b.put([1], [[nxt]])
        np.testing.assert_allclose(
            np.asarray(dec_b[0], np.float32),
            np.asarray(dec_a[0], np.float32), atol=0.15)

    def test_staged_device_latents_restore(self, tiny_model):
        """model.restore_kv on an HBM-resident ``jax.Array`` slab (no
        H2D ship — the hybrid-engine handoff / marginal-bench path)
        matches the host-latents path."""
        cfg, model, params = tiny_model
        rng = np.random.default_rng(15)
        prompt = list(rng.integers(0, cfg.vocab_size, (11,)))

        engine_a = make_engine(cfg, params)
        logits_a, latents = engine_a.put([1], [prompt])
        nxt = int(np.argmax(logits_a[0]))
        dec_a, _ = engine_a.put([1], [[nxt]])

        engine_b = make_engine(cfg, params)
        # the engine's own group staging, then the model-level call on
        # an HBM-resident slab (exactly the marginal-bench sequence)
        items = [(1, np.asarray(prompt, np.int32),
                  np.asarray(latents[0]))]
        lat, start, t_len, tables, seqs = \
            engine_b._stage_restore_group(items)
        engine_b.model.restore_kv(engine_b.cache, jax.device_put(lat),
                                  start, tables, t_len)
        for seq in seqs:
            seq.post_forward()
        assert engine_b.state.get_sequence(1).seen_tokens == len(prompt)
        dec_b, _ = engine_b.put([1], [[nxt]])
        np.testing.assert_allclose(dec_b[0], dec_a[0], atol=2e-2)

    def test_defer_fetch_put(self, tiny_model):
        """put(defer_fetch=True) returns raw device logits (no host
        sync) that match the normal path; incompatible modes reject."""
        cfg, model, params = tiny_model
        rng = np.random.default_rng(16)
        prompt = list(rng.integers(0, cfg.vocab_size, (9,)))

        prompt2 = list(rng.integers(0, cfg.vocab_size, (9,)))
        engine = make_engine(cfg, params,
                             hcache={"enable_latents": False})
        ref, _ = engine.put([1, 2], [prompt, prompt2])
        engine.flush(1)
        engine.flush(2)
        logits_out, _ = engine.put([1, 2], [prompt, prompt2],
                                   defer_fetch=True)
        assert all(x is not None for x in logits_out)
        for i in range(2):   # every uid resolves to its own lane
            arr, lane = logits_out[i]
            assert isinstance(arr, jax.Array)
            np.testing.assert_allclose(np.asarray(arr)[lane], ref[i],
                                       atol=2e-2)

        # latent capture on -> the plain-path guard rejects
        engine_lat = make_engine(cfg, params)
        with pytest.raises(ValueError, match="defer_fetch"):
            engine_lat.put([2], [prompt], defer_fetch=True)

    def test_restore_admission_is_atomic(self, tiny_model):
        """A restore that cannot fully fit must not touch any state."""
        cfg, model, params = tiny_model
        rng = np.random.default_rng(14)
        prompts = [list(rng.integers(0, cfg.vocab_size, (8,)))
                   for _ in range(9)]
        engine = make_engine(cfg, params)          # limit: 8 tracked
        _, latents = engine.put([0], [prompts[0]])
        engine.flush(0)
        free0 = engine.state.free_blocks
        with pytest.raises(SchedulingError):
            engine.restore_kv(list(range(9)), prompts,
                              [latents[0]] * 9)
        assert engine.state.n_tracked_sequences == 0
        assert engine.state.free_blocks == free0


class TestFusedSampling:
    """On-device sampling in the fused decode loop."""

    def test_topk1_equals_greedy(self, tiny_model):
        cfg, model, params = tiny_model
        engine = make_engine(cfg, params,
                             hcache={"enable_latents": False})
        rng = np.random.default_rng(15)
        prompt = list(rng.integers(0, cfg.vocab_size, (5,)))
        greedy, _ = engine.generate_fused([prompt], max_new_tokens=6)
        topk1, _ = engine.generate_fused([prompt], max_new_tokens=6,
                                         temperature=0.7, top_k=1)
        assert topk1 == greedy

    def test_seed_reproducible_and_varies(self, tiny_model):
        cfg, model, params = tiny_model
        engine = make_engine(cfg, params,
                             hcache={"enable_latents": False})
        rng = np.random.default_rng(16)
        prompt = list(rng.integers(0, cfg.vocab_size, (5,)))
        kw = dict(max_new_tokens=8, temperature=1.5, top_p=0.9)
        a, _ = engine.generate_fused([prompt], seed=1, **kw)
        b, _ = engine.generate_fused([prompt], seed=1, **kw)
        assert a == b
        seeds = [engine.generate_fused([prompt], seed=s, **kw)[0]
                 for s in range(2, 8)]
        assert any(s != a for s in seeds)

    def test_sampled_tokens_stay_in_nucleus(self, tiny_model):
        """With tight top_p every sampled token must be in the nucleus
        of the reference distribution at its step."""
        cfg, model, params = tiny_model
        engine = make_engine(cfg, params,
                             hcache={"enable_latents": False})
        rng = np.random.default_rng(17)
        prompt = list(rng.integers(0, cfg.vocab_size, (6,)))
        outs, _ = engine.generate_fused([prompt], max_new_tokens=5,
                                        temperature=1.0, top_p=0.5,
                                        seed=3)
        seq = list(prompt)
        for tok in outs[0]:
            ref = full_logits(model, params, seq)[-1].astype(np.float64)
            p = np.exp(ref - ref.max())
            p /= p.sum()
            order = np.argsort(p)[::-1]
            # slack over the sampler's 0.5: engine logits differ from
            # the reference forward by ~2e-2, which can flip tokens at
            # the nucleus boundary
            keep = np.cumsum(p[order]) - p[order] < 0.6
            nucleus = set(order[keep].tolist())
            assert tok in nucleus
            seq.append(tok)

    def test_fused_logprobs_match_reference(self, tiny_model):
        """Per-token logprobs from the fused loop == log-softmax of the
        reference forward at each position (greedy path)."""
        cfg, model, params = tiny_model
        engine = make_engine(cfg, params,
                             hcache={"enable_latents": False})
        rng = np.random.default_rng(18)
        prompt = list(rng.integers(0, cfg.vocab_size, (7,)))
        outs, _, lps = engine.generate_fused([prompt], max_new_tokens=5,
                                             return_logprobs=True)
        assert lps[0].shape == (5,)
        seq = list(prompt)
        for tok, lp in zip(outs[0], lps[0]):
            ref = full_logits(model, params, seq)[-1].astype(np.float64)
            ref_lp = ref[tok] - (np.log(np.exp(ref - ref.max()).sum())
                                 + ref.max())
            np.testing.assert_allclose(lp, ref_lp, atol=5e-2)
            seq.append(tok)


class TestFusedEosEarlyExit:
    """has_eos switches the fused loop to a while_loop that exits when
    every lane is done; results must match the no-eos path up to the
    EOS truncation."""

    def test_eos_path_matches_scan_path(self, tiny_model):
        cfg, model, params = tiny_model
        engine = make_engine(cfg, params,
                             hcache={"enable_latents": False})
        rng = np.random.default_rng(20)
        prompts = [list(rng.integers(0, cfg.vocab_size, (n,)))
                   for n in (6, 4)]
        full, _ = engine.generate_fused(prompts, max_new_tokens=8)
        # pick an eos that actually occurs mid-stream for lane 0
        eos = full[0][3]
        cut, _ = engine.generate_fused(prompts, max_new_tokens=8,
                                       eos_token_id=eos)
        assert cut[0] == full[0][:full[0].index(eos) + 1]
        # lane 1: identical prefix up to ITS first eos (if any)
        exp1 = full[1][:full[1].index(eos) + 1] if eos in full[1] \
            else full[1]
        assert cut[1] == exp1

    def test_first_token_eos(self, tiny_model):
        cfg, model, params = tiny_model
        engine = make_engine(cfg, params,
                             hcache={"enable_latents": False})
        prompt = [2, 7, 1]
        base, _ = engine.generate_fused([prompt], max_new_tokens=1)
        eos = base[0][0]
        outs, _ = engine.generate_fused([prompt], max_new_tokens=10,
                                        eos_token_id=eos)
        assert outs[0] == [eos]

    def test_eos_with_logprobs_and_latents(self, tiny_model):
        cfg, model, params = tiny_model
        engine = make_engine(cfg, params)
        rng = np.random.default_rng(21)
        prompt = list(rng.integers(0, cfg.vocab_size, (5,)))
        full, _ = engine.generate_fused([prompt], max_new_tokens=8)
        eos = full[0][4]
        outs, lat, lps = engine.generate_fused(
            [prompt], max_new_tokens=8, eos_token_id=eos,
            return_logprobs=True)
        assert outs[0] == full[0][:5]
        assert len(lps[0]) == len(outs[0])
        assert lat[0].shape[1] == len(prompt) + len(outs[0]) - 1


class TestSpecLatentCapture:
    """put_spec under latent preemption: the latent-capturing tail
    forward returns accepted-span latents that are restore-grade —
    a speculated-then-preempted sequence resumes through restore_kv
    exactly like a plainly decoded one."""

    def _greedy_ref(self, cfg, params, prompt, steps):
        eng = make_engine(cfg, params)
        logits, _ = eng.put([0], [prompt])
        out = [int(np.argmax(logits[0]))]
        for _ in range(steps - 1):
            logits, _ = eng.put([0], [[out[-1]]])
            out.append(int(np.argmax(logits[0])))
        return out

    def test_put_spec_captures_accepted_span_latents(self, tiny_model):
        cfg, params = tiny_model[0], tiny_model[2]
        rng = np.random.default_rng(31)
        prompt = list(rng.integers(0, cfg.vocab_size, (9,)))
        ref = self._greedy_ref(cfg, params, prompt, 8)

        eng = make_engine(cfg, params)          # latents ON (default)
        assert eng.spec_latent_capture is True
        logits, lat0 = eng.put([5], [prompt])
        assert lat0[0].shape == (cfg.n_layer, len(prompt),
                                 cfg.hidden_size)
        out = [int(np.argmax(logits[0]))]
        chunks = [np.asarray(lat0[0])]
        while len(out) < 8:
            # draft from the reference stream: prefix-accepted
            k = len(out)
            draft = ref[k:k + 2][:8 - k - 1]
            emitted, lats = eng.put_spec([5], [[out[-1]] + draft])
            assert len(emitted[0]) >= 1
            # the latent chunk covers EXACTLY the fed+accepted span
            assert lats[0] is not None
            assert lats[0].shape == (cfg.n_layer, len(emitted[0]),
                                     cfg.hidden_size)
            out.extend(emitted[0])
            chunks.append(np.asarray(lats[0]))
        # greedy-exact: the speculated stream IS the greedy stream
        assert out[:8] == ref
        # cumulative latents cover prompt + every fed token (all but
        # the still-unfed last emission)
        total = np.concatenate(chunks, axis=1)
        assert total.shape[1] == len(prompt) + len(out) - 1

    def test_spec_latents_are_restore_grade(self, tiny_model):
        cfg, params = tiny_model[0], tiny_model[2]
        rng = np.random.default_rng(32)
        prompt = list(rng.integers(0, cfg.vocab_size, (8,)))
        ref = self._greedy_ref(cfg, params, prompt, 6)

        eng = make_engine(cfg, params)
        logits, lat0 = eng.put([3], [prompt])
        out = [int(np.argmax(logits[0]))]
        chunks = [np.asarray(lat0[0])]
        while len(out) < 5:
            k = len(out)
            emitted, lats = eng.put_spec(
                [3], [[out[-1]] + ref[k:k + 2][:5 - k - 1]])
            out.extend(emitted[0])
            chunks.append(np.asarray(lats[0]))
        # preempt to latents: drop the KV entirely, keep the chunks
        eng.flush(3)
        fed = prompt + out[:-1]
        eng.restore_kv([3], [fed], [np.concatenate(chunks, axis=1)])
        logits, _ = eng.put([3], [[out[-1]]])
        resumed = int(np.argmax(logits[0]))

        # ground truth: the same stream decoded without interruption
        uninterrupted = make_engine(cfg, params)
        l2, _ = uninterrupted.put([3], [prompt])
        for t in out:
            l2, _ = uninterrupted.put([3], [[t]])
        assert resumed == int(np.argmax(l2[0]))
        np.testing.assert_allclose(np.asarray(logits[0]),
                                   np.asarray(l2[0]), atol=1e-3)

    def test_put_spec_exact_kv_mode_still_returns_none(self, tiny_model):
        cfg, params = tiny_model[0], tiny_model[2]
        eng = make_engine(cfg, params,
                          hcache={"enable_latents": False})
        logits, lat = eng.put([1], [[2, 7, 1, 8]])
        assert lat[0] is None
        emitted, lats = eng.put_spec(
            [1], [[int(np.argmax(logits[0]))]])
        assert lats == [None]
