"""Tensor-parallel inference: engine on a tensor=2 mesh must produce the
same logits as single-chip (reference: v2 model TP sharding + per-layer
allreduce, llama_v2/model.py:160,169)."""

import jax
import numpy as np
import pytest

from hcache_deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
from hcache_deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
from hcache_deepspeed_tpu.models.llama import LlamaForCausalLM, llama_tiny
from hcache_deepspeed_tpu.parallel import topology as topo_mod


def _setup():
    cfg = llama_tiny(max_positions=128)
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 256, (2, 16), dtype=np.int32)}
    params = model.init(jax.random.PRNGKey(0), batch,
                        train=False)["params"]
    return cfg, params


def _engine(cfg, params, topology=None):
    return InferenceEngineV2(
        cfg, params, topology=topology,
        config=RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 8,
                           "max_ragged_batch_size": 128,
                           "max_ragged_sequence_count": 4,
                           "max_context": 128},
            kv_cache={"block_size": 16, "num_blocks": 24,
                      "cache_dtype": "float32"}))


@pytest.fixture
def tp_topo(eight_devices):
    topo = topo_mod.initialize_topology(
        topo_mod.TopologySpec(data=4, tensor=2))
    yield topo
    topo_mod.reset_topology()


class TestTPInference:
    def test_prefill_decode_logits_match_single_chip(self, tp_topo):
        cfg, params = _setup()
        rng = np.random.default_rng(1)
        prompt = rng.integers(0, 256, (20,), dtype=np.int32).tolist()

        ref = _engine(cfg, params)
        tp = _engine(cfg, params, topology=tp_topo)

        lr, _ = ref.put([1], [prompt])
        lt, _ = tp.put([1], [prompt])
        np.testing.assert_allclose(np.asarray(lr), np.asarray(lt),
                                   atol=2e-4)
        # a few decode steps: cache state must track identically
        tok = int(np.argmax(np.asarray(lr)[0]))
        for _ in range(4):
            lr, _ = ref.put([1], [[tok]])
            lt, _ = tp.put([1], [[tok]])
            np.testing.assert_allclose(np.asarray(lr), np.asarray(lt),
                                       atol=2e-4)
            tok = int(np.argmax(np.asarray(lr)[0]))

    def test_kv_cache_sharded_on_tensor(self, tp_topo):
        cfg, params = _setup()
        tp = _engine(cfg, params, topology=tp_topo)
        spec = tp.cache.k.sharding.spec
        assert "tensor" in str(spec), spec

    def test_restore_kv_under_tp(self, tp_topo):
        cfg, params = _setup()
        tp = _engine(cfg, params, topology=tp_topo)
        rng = np.random.default_rng(2)
        prompt = rng.integers(0, 256, (20,), dtype=np.int32).tolist()
        logits, latents = tp.put([5], [prompt])
        tok = int(np.argmax(np.asarray(logits)[0]))
        l_direct, _ = tp.put([5], [[tok]])
        # evict, restore from latents, decode again: same logits
        tp.flush(5)
        tp.restore_kv([5], [prompt], [latents[0]])
        l_restored, _ = tp.put([5], [[tok]])
        np.testing.assert_allclose(np.asarray(l_direct),
                                   np.asarray(l_restored), atol=2e-4)

    def test_staged_latents_reshard_onto_cache_mesh(self, tp_topo):
        """A staged (jax.Array) latent slab committed to a SINGLE device
        must be resharded onto the sharded cache's mesh, not handed to
        the jitted restore as-is (incompatible committed devices)."""
        cfg, params = _setup()
        tp = _engine(cfg, params, topology=tp_topo)
        rng = np.random.default_rng(7)
        prompt = rng.integers(0, 256, (20,), dtype=np.int32).tolist()
        logits, latents = tp.put([5], [prompt])
        tok = int(np.argmax(np.asarray(logits)[0]))
        l_direct, _ = tp.put([5], [[tok]])
        tp.flush(5)
        items = [(5, np.asarray(prompt, np.int32),
                  np.asarray(latents[0]))]
        lat, start, t_len, tables, seqs = tp._stage_restore_group(items)
        slab = jax.device_put(lat, jax.devices()[0])   # one device only
        tp.model.restore_kv(tp.cache, slab, start, tables, t_len)
        for seq in seqs:
            seq.post_forward()
        l_restored, _ = tp.put([5], [[tok]])
        np.testing.assert_allclose(np.asarray(l_direct),
                                   np.asarray(l_restored), atol=2e-4)

    def test_indivisible_heads_rejected(self, tp_topo):
        cfg, params = _setup()
        import dataclasses
        bad = dataclasses.replace(cfg, n_head=3, n_kv_head=3)
        with pytest.raises(ValueError, match="divisible"):
            InferenceEngineV2(bad, params, topology=tp_topo)

    def test_generate_fused_under_tp(self, tp_topo):
        """The fused decode loop (scan and the EOS while_loop variant,
        both wrapping the shard_map'd forward) must match single-chip
        greedy generation."""
        cfg, params = _setup()
        rng = np.random.default_rng(2)
        prompts = [rng.integers(0, 256, (9,), dtype=np.int32).tolist(),
                   rng.integers(0, 256, (5,), dtype=np.int32).tolist()]

        ref = _engine(cfg, params)
        tp = _engine(cfg, params, topology=tp_topo)
        outs_ref, _ = ref.generate_fused(prompts, max_new_tokens=6)
        outs_tp, _, lps = tp.generate_fused(prompts, max_new_tokens=6,
                                            return_logprobs=True)
        assert outs_tp == outs_ref
        assert all(lp.shape == (6,) for lp in lps)

        eos = outs_ref[0][2]
        cut_ref, _ = ref.generate_fused(prompts, max_new_tokens=6,
                                        eos_token_id=eos)
        cut_tp, _ = tp.generate_fused(prompts, max_new_tokens=6,
                                      eos_token_id=eos)
        assert cut_tp == cut_ref

    def test_speculative_lookup_under_tp(self, tp_topo):
        """Both speculative paths (host verify dispatch and the fused
        on-device loop) wrap the TP tail-logits forward (vocab
        all-gather on the tail axis) — outputs must match single-chip
        greedy exactly."""
        cfg, params = _setup()

        def spec_engine(topology=None):
            return InferenceEngineV2(
                cfg, params, topology=topology,
                config=RaggedInferenceEngineConfig(
                    state_manager={"max_tracked_sequences": 8,
                                   "max_ragged_batch_size": 128,
                                   "max_ragged_sequence_count": 4,
                                   "max_context": 128},
                    kv_cache={"block_size": 16, "num_blocks": 24,
                              "cache_dtype": "float32"},
                    hcache={"enable_latents": False}))

        rng = np.random.default_rng(4)
        prompt = rng.integers(0, 256, (20,), dtype=np.int32).tolist()
        [want] = spec_engine().generate([prompt], max_new_tokens=10)
        host, _ = spec_engine(tp_topo).generate_lookup(
            [prompt], max_new_tokens=10, ngram=2, max_draft=3)
        assert host[0] == want
        fused, _ = spec_engine(tp_topo).generate_lookup_fused(
            [prompt], max_new_tokens=10, ngram=2, max_draft=3)
        assert fused[0] == want
