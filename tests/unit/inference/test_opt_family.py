"""OPT family through the ragged engine (reference: the opt policy in
engine_factory.py:69 / module_inject/containers/opt.py)."""

import jax
import numpy as np
import pytest

from hcache_deepspeed_tpu.inference import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig,
                                            build_hf_engine)
from hcache_deepspeed_tpu.inference.model_opt import PagedOPTModel
from hcache_deepspeed_tpu.models.opt import (OPTForCausalLM, opt_tiny)


@pytest.fixture(scope="module")
def tiny_opt():
    cfg = opt_tiny(use_flash=False)
    model = OPTForCausalLM(cfg)
    batch = {"input_ids": np.zeros((1, 8), np.int32)}
    params = model.init(jax.random.PRNGKey(0), batch, train=False)["params"]
    return cfg, model, params


def make_engine(cfg, params):
    return InferenceEngineV2(
        cfg, params,
        config=RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 8, "max_context": 128},
            kv_cache={"block_size": 16, "num_blocks": 24,
                      "cache_dtype": "float32"}))


def full_logits(model, params, tokens):
    out = model.apply({"params": params},
                      {"input_ids": np.asarray(tokens, np.int32)[None]},
                      train=False, return_logits=True)
    return np.asarray(out)[0]


class TestOPTPagedInference:

    def test_engine_selects_opt_model(self, tiny_opt):
        cfg, _, params = tiny_opt
        engine = make_engine(cfg, params)
        assert isinstance(engine.model, PagedOPTModel)

    def test_training_model_trains(self, tiny_opt):
        cfg, model, params = tiny_opt
        rng = np.random.default_rng(0)
        batch = {"input_ids": rng.integers(0, cfg.vocab_size, (2, 16),
                                           dtype=np.int32)}

        def loss_fn(p):
            return model.apply({"params": p}, batch, train=True)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        assert np.isfinite(float(loss))
        assert all(np.isfinite(np.asarray(g)).all()
                   for g in jax.tree.leaves(grads))

    def test_prefill_decode_parity(self, tiny_opt):
        cfg, model, params = tiny_opt
        engine = make_engine(cfg, params)
        rng = np.random.default_rng(1)
        tokens = list(rng.integers(0, cfg.vocab_size, (11,)))
        logits, _ = engine.put([1], [tokens])
        np.testing.assert_allclose(logits[0],
                                   full_logits(model, params, tokens)[-1],
                                   atol=2e-2)
        for _ in range(4):
            nxt = int(np.argmax(logits[0]))
            tokens.append(nxt)
            logits, _ = engine.put([1], [[nxt]])
            np.testing.assert_allclose(
                logits[0], full_logits(model, params, tokens)[-1],
                atol=2e-2)

    def test_restore_equals_recompute(self, tiny_opt):
        cfg, model, params = tiny_opt
        rng = np.random.default_rng(2)
        prompt = list(rng.integers(0, cfg.vocab_size, (9,)))
        engine_a = make_engine(cfg, params)
        logits_a, latents = engine_a.put([1], [prompt])
        nxt = int(np.argmax(logits_a[0]))
        dec_a, _ = engine_a.put([1], [[nxt]])

        engine_b = make_engine(cfg, params)
        engine_b.restore_kv([1], [prompt], [latents[0]])
        dec_b, _ = engine_b.put([1], [[nxt]])
        np.testing.assert_allclose(dec_b[0], dec_a[0], atol=2e-2)

    def test_hf_factory_opt(self, tiny_opt):
        cfg, _, params = tiny_opt
        hf = {"model_type": "opt", "vocab_size": cfg.vocab_size,
              "hidden_size": cfg.hidden_size, "ffn_dim": cfg.ffn_dim,
              "num_hidden_layers": cfg.n_layer,
              "num_attention_heads": cfg.n_head,
              "max_position_embeddings": cfg.max_positions,
              "torch_dtype": "float32"}
        engine = build_hf_engine(
            hf, params,
            engine_config=RaggedInferenceEngineConfig(
                state_manager={"max_tracked_sequences": 4,
                               "max_context": 128},
                kv_cache={"block_size": 16, "num_blocks": 24}))
        assert isinstance(engine.model, PagedOPTModel)
        logits, _ = engine.put([1], [[1, 2, 3]])
        assert np.isfinite(np.asarray(logits)).all()
