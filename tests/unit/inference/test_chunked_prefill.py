"""Chunked prefill (reference: FastGen's Dynamic SplitFuse — long
prompts process in fixed chunks so the per-forward token budget bounds
latency, not prompt length)."""

import jax
import numpy as np
import pytest

from hcache_deepspeed_tpu.inference import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig,
                                            SchedulingError)
from hcache_deepspeed_tpu.models.llama import LlamaForCausalLM, llama_tiny


@pytest.fixture(scope="module")
def tiny():
    cfg = llama_tiny(max_positions=256, use_flash=False)
    model = LlamaForCausalLM(cfg)
    batch = {"input_ids": np.zeros((1, 8), np.int32)}
    params = model.init(jax.random.PRNGKey(0), batch, train=False)["params"]
    return cfg, model, params


def make_engine(cfg, params, chunk=0, batch_budget=256):
    return InferenceEngineV2(
        cfg, params,
        config=RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 8,
                           "max_ragged_batch_size": batch_budget,
                           "max_ragged_sequence_count": 8,
                           "max_context": 256,
                           "prefill_chunk": chunk},
            kv_cache={"block_size": 16, "num_blocks": 40,
                      "cache_dtype": "float32"}))


def full_logits(model, params, tokens):
    out = model.apply({"params": params},
                      {"input_ids": np.asarray(tokens, np.int32)[None]},
                      train=False, return_logits=True)
    return np.asarray(out)[0]


def test_long_prompt_beyond_batch_budget(tiny):
    """A 100-token prompt against a 32-token forward budget: rejected
    unchunked, exact with prefill_chunk=32."""
    cfg, model, params = tiny
    rng = np.random.default_rng(0)
    prompt = list(rng.integers(0, cfg.vocab_size, (100,)))

    with pytest.raises(SchedulingError):
        make_engine(cfg, params, chunk=0, batch_budget=32).put(
            [1], [prompt])

    engine = make_engine(cfg, params, chunk=32, batch_budget=32)
    logits, latents = engine.put([1], [prompt])
    np.testing.assert_allclose(logits[0],
                               full_logits(model, params, prompt)[-1],
                               atol=2e-2)
    assert latents[0].shape[1] == 100   # stitched across chunks


def test_chunked_equals_unchunked(tiny):
    cfg, model, params = tiny
    rng = np.random.default_rng(1)
    prompt = list(rng.integers(0, cfg.vocab_size, (70,)))
    a = make_engine(cfg, params, chunk=0)
    b = make_engine(cfg, params, chunk=16)
    la, lata = a.put([1], [prompt])
    lb, latb = b.put([1], [prompt])
    np.testing.assert_allclose(lb[0], la[0], atol=2e-2)
    np.testing.assert_allclose(np.asarray(latb[0]), np.asarray(lata[0]),
                               atol=2e-2)
    # decode continues identically
    nxt = int(np.argmax(la[0]))
    da, _ = a.put([1], [[nxt]])
    db, _ = b.put([1], [[nxt]])
    np.testing.assert_allclose(db[0], da[0], atol=2e-2)


def test_restore_from_stitched_latents(tiny):
    """HCache restore works from latents assembled across chunks."""
    cfg, model, params = tiny
    rng = np.random.default_rng(2)
    prompt = list(rng.integers(0, cfg.vocab_size, (70,)))
    a = make_engine(cfg, params, chunk=16)
    la, latents = a.put([1], [prompt])
    nxt = int(np.argmax(la[0]))
    da, _ = a.put([1], [[nxt]])

    b = make_engine(cfg, params, chunk=16)
    b.restore_kv([1], [prompt], [latents[0]])
    db, _ = b.put([1], [[nxt]])
    np.testing.assert_allclose(db[0], da[0], atol=2e-2)


def test_generate_with_chunked_prefill(tiny):
    cfg, model, params = tiny
    rng = np.random.default_rng(3)
    prompts = [list(rng.integers(0, cfg.vocab_size, (n,)))
               for n in (60, 9)]
    chunked = make_engine(cfg, params, chunk=16, batch_budget=48)
    plain = make_engine(cfg, params, chunk=0, batch_budget=256)
    outs_c = chunked.generate(prompts, max_new_tokens=6)
    outs_p = plain.generate(prompts, max_new_tokens=6)
    assert outs_c == outs_p
