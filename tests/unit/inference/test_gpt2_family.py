"""GPT-2 family in the ragged engine (reference: the v1 gpt2 injection
container + v2 per-arch model implementations) and the dropless
grouped-GEMM MoE (cutlass_ops/moe_gemm analog)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hcache_deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
from hcache_deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
from hcache_deepspeed_tpu.models.gpt2 import GPT2LMHeadModel, gpt2_tiny


def _engine(cfg, params):
    return InferenceEngineV2(cfg, params, config=RaggedInferenceEngineConfig(
        state_manager={"max_tracked_sequences": 8,
                       "max_ragged_batch_size": 128,
                       "max_ragged_sequence_count": 4,
                       "max_context": 128},
        kv_cache={"block_size": 16, "num_blocks": 24,
                  "cache_dtype": "float32"}))


def _setup():
    cfg = gpt2_tiny(n_positions=128, use_flash=False)
    model = GPT2LMHeadModel(cfg)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 256, (1, 16), dtype=np.int32)}
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    return cfg, model, params


class TestPagedGPT2:
    def test_prefill_matches_training_model_logits(self):
        cfg, model, params = _setup()
        rng = np.random.default_rng(1)
        prompt = rng.integers(0, 256, (20,)).astype(np.int32)
        eng = _engine(cfg, params)
        logits, _ = eng.put([1], [prompt.tolist()])
        # oracle: the training model's full forward, last position
        full = model.apply({"params": params},
                           {"input_ids": prompt[None]},
                           return_logits=True)
        np.testing.assert_allclose(np.asarray(logits)[0],
                                   np.asarray(full)[0, -1], atol=2e-4)

    def test_decode_matches_training_model(self):
        cfg, model, params = _setup()
        rng = np.random.default_rng(2)
        prompt = rng.integers(0, 256, (9,)).astype(np.int32)
        eng = _engine(cfg, params)
        logits, _ = eng.put([5], [prompt.tolist()])
        tok = int(np.argmax(np.asarray(logits)[0]))
        seq = list(prompt) + [tok]
        for _ in range(3):
            logits, _ = eng.put([5], [[tok]])
            full = model.apply({"params": params},
                               {"input_ids": np.asarray(seq)[None]},
                               return_logits=True)
            np.testing.assert_allclose(
                np.asarray(logits)[0], np.asarray(full)[0, -1], atol=2e-4)
            tok = int(np.argmax(np.asarray(logits)[0]))
            seq.append(tok)

    def test_restore_kv_roundtrip(self):
        cfg, model, params = _setup()
        rng = np.random.default_rng(3)
        prompt = rng.integers(0, 256, (20,)).astype(np.int32).tolist()
        eng = _engine(cfg, params)
        logits, latents = eng.put([7], [prompt])
        tok = int(np.argmax(np.asarray(logits)[0]))
        direct, _ = eng.put([7], [[tok]])
        eng.flush(7)
        eng.restore_kv([7], [prompt], [latents[0]])
        restored, _ = eng.put([7], [[tok]])
        np.testing.assert_allclose(np.asarray(direct),
                                   np.asarray(restored), atol=2e-4)

    def test_generate_loop(self):
        cfg, _, params = _setup()
        eng = _engine(cfg, params)
        outs = eng.generate([[1, 2, 3], [9, 9]], max_new_tokens=4)
        assert [len(o) for o in outs] == [4, 4]

    def test_factory_family(self):
        from hcache_deepspeed_tpu.inference.factory import MODEL_FAMILIES
        mc = MODEL_FAMILIES["gpt2"]({"model_type": "gpt2", "n_embd": 64,
                                     "n_layer": 2, "n_head": 4,
                                     "vocab_size": 256})
        assert mc.n_embd == 64 and mc.head_dim == 16
        assert "phi3" in MODEL_FAMILIES


class TestDroplessMoE:
    def test_grouped_matmul_parity(self):
        from hcache_deepspeed_tpu.ops.grouped_gemm import (
            ragged_grouped_matmul, reference_grouped_matmul)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((12, 8)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((3, 8, 6)), jnp.float32)
        gs = jnp.asarray([5, 0, 7], jnp.int32)  # empty group included
        a = reference_grouped_matmul(x, w, gs)
        b = ragged_grouped_matmul(x, w, gs)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5)

    def test_dropless_no_tokens_dropped(self):
        """Unlike the capacity layer, every token contributes: with k=1
        and all tokens routed to one expert, outputs match that expert's
        dense FFN (capacity layers would drop the overflow)."""
        from hcache_deepspeed_tpu.moe.dropless import DroplessMoEMLP
        rng = np.random.default_rng(1)
        # positive activations so the biased gate column dominates
        x = jnp.asarray(np.abs(rng.standard_normal((2, 8, 16))),
                        jnp.float32)
        layer = DroplessMoEMLP(num_experts=4, hidden_size=16,
                               intermediate_size=32, k=1)
        params = layer.init(jax.random.PRNGKey(0), x)
        # force all routing to expert 2 by biasing the gate
        wg = np.zeros((16, 4), np.float32)
        wg[:, 2] = 1.0
        params = jax.tree_util.tree_map_with_path(
            lambda p, leaf: jnp.asarray(wg) if "wg" in str(p) else leaf,
            params)
        out, aux = layer.apply(params, x)
        p = params["params"]
        h = jax.nn.silu(x @ p["experts"]["w1"][2]) * (x @ p["experts"]["w3"][2])
        expect = h @ p["experts"]["w2"][2]
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   atol=1e-5)

    def test_dropless_trains(self):
        from hcache_deepspeed_tpu.moe.dropless import DroplessMoEMLP
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.float32)
        tgt = jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.float32)
        layer = DroplessMoEMLP(num_experts=4, hidden_size=16,
                               intermediate_size=32, k=2)
        params = layer.init(jax.random.PRNGKey(0), x)

        def loss(p):
            out, aux = layer.apply(p, x)
            return ((out - tgt) ** 2).mean() + 0.01 * aux

        l0 = float(loss(params))
        g = jax.jit(jax.grad(loss))(params)
        params2 = jax.tree.map(lambda p, gg: p - 0.1 * gg, params, g)
        assert float(loss(params2)) < l0
