"""Tensor-parallel MoE serving: expert FFN dims sharded on ``tensor``
(w1/w3 column, w2 row, psum after the combine) must match single-chip
logits (reference: TP-sharded MoE inference,
inference/v2/model_implementations/sharding/ + cutlass MoE module)."""

import jax
import numpy as np
import pytest

from hcache_deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
from hcache_deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
from hcache_deepspeed_tpu.inference.model_moe import PagedMoEModel
from hcache_deepspeed_tpu.models.mixtral import (MixtralForCausalLM,
                                                 mixtral_tiny)
from hcache_deepspeed_tpu.parallel import topology as topo_mod


def _setup():
    cfg = mixtral_tiny(max_positions=128, use_flash=False, dropless=True,
                       hidden_size=64, intermediate_size=128)
    model = MixtralForCausalLM(cfg)
    batch = {"input_ids": np.zeros((2, 16), np.int32)}
    params = model.init(jax.random.PRNGKey(0), batch,
                        train=False)["params"]
    return cfg, params


def _engine(cfg, params, topology=None):
    return InferenceEngineV2(
        cfg, params, topology=topology,
        config=RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 8,
                           "max_ragged_batch_size": 128,
                           "max_ragged_sequence_count": 4,
                           "max_context": 128},
            kv_cache={"block_size": 16, "num_blocks": 24,
                      "cache_dtype": "float32"}))


@pytest.fixture
def tp_topo(eight_devices):
    topo = topo_mod.initialize_topology(
        topo_mod.TopologySpec(data=4, tensor=2))
    yield topo
    topo_mod.reset_topology()


class TestTPMoEServing:
    def test_logits_match_single_chip(self, tp_topo):
        cfg, params = _setup()
        ref = _engine(cfg, params)
        tp = _engine(cfg, params, topology=tp_topo)
        assert isinstance(tp.model, PagedMoEModel)

        rng = np.random.default_rng(0)
        prompt = rng.integers(0, cfg.vocab_size, (20,)).tolist()
        lr, _ = ref.put([1], [prompt])
        lt, _ = tp.put([1], [prompt])
        np.testing.assert_allclose(np.asarray(lr), np.asarray(lt),
                                   atol=2e-4)
        tok = int(np.argmax(np.asarray(lr)[0]))
        for _ in range(3):
            lr, _ = ref.put([1], [[tok]])
            lt, _ = tp.put([1], [[tok]])
            np.testing.assert_allclose(np.asarray(lr), np.asarray(lt),
                                       atol=2e-4)
            tok = int(np.argmax(np.asarray(lr)[0]))

    def test_qwen2_moe_shared_expert_tp(self, tp_topo):
        """Shared expert shards like a dense MLP; logits match
        single-chip."""
        from hcache_deepspeed_tpu.models.mixtral import qwen2_moe_tiny
        cfg = qwen2_moe_tiny(max_positions=128, use_flash=False,
                             hidden_size=64, intermediate_size=128,
                             shared_expert_intermediate_size=96)
        model = MixtralForCausalLM(cfg)
        params = model.init(jax.random.PRNGKey(0),
                            {"input_ids": np.zeros((2, 16), np.int32)},
                            train=False)["params"]
        ref = _engine(cfg, params)
        tp = _engine(cfg, params, topology=tp_topo)
        sgp = tp.model.params["layers"]["mlp"]["moe"]["shared_gate_proj"]
        assert "tensor" in str(sgp["kernel"].sharding.spec)
        rng = np.random.default_rng(3)
        prompt = rng.integers(0, cfg.vocab_size, (16,)).tolist()
        lr, _ = ref.put([1], [prompt])
        lt, _ = tp.put([1], [prompt])
        np.testing.assert_allclose(np.asarray(lr), np.asarray(lt),
                                   atol=2e-4)

    def test_expert_weights_sharded(self, tp_topo):
        cfg, params = _setup()
        tp = _engine(cfg, params, topology=tp_topo)
        w1 = tp.model.params["layers"]["mlp"]["moe"]["experts"]["w1"]
        assert "tensor" in str(w1.sharding.spec)
        wg = tp.model.params["layers"]["mlp"]["moe"]["wg"]
        # router replicated and fp32
        assert wg.dtype == np.float32
        assert "tensor" not in str(wg.sharding.spec)
