"""Continuous batching in generate() (reference: the FastGen dynamic
scheduler — new prompts join the ragged batch while others decode,
blocks freed by finished sequences admit pending ones mid-flight)."""

import jax
import numpy as np
import pytest

from hcache_deepspeed_tpu.inference import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig,
                                            SchedulingError)
from hcache_deepspeed_tpu.models.llama import LlamaForCausalLM, llama_tiny


@pytest.fixture(scope="module")
def tiny():
    cfg = llama_tiny(max_positions=128, use_flash=False)
    model = LlamaForCausalLM(cfg)
    batch = {"input_ids": np.zeros((1, 8), np.int32)}
    params = model.init(jax.random.PRNGKey(0), batch, train=False)["params"]
    return cfg, model, params


def make_engine(cfg, params, num_blocks=24):
    return InferenceEngineV2(
        cfg, params,
        config=RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 16,
                           "max_ragged_batch_size": 256,
                           "max_ragged_sequence_count": 8,
                           "max_context": 128},
            kv_cache={"block_size": 16, "num_blocks": num_blocks,
                      "cache_dtype": "float32"}))


def test_greedy_equals_sequential(tiny):
    """Batched continuous generation must produce exactly what one-at-a-
    time greedy generation produces."""
    cfg, model, params = tiny
    rng = np.random.default_rng(0)
    prompts = [list(rng.integers(0, cfg.vocab_size, (n,)))
               for n in (5, 9, 7)]
    together = make_engine(cfg, params).generate(prompts,
                                                 max_new_tokens=6)
    for p, got in zip(prompts, together):
        solo = make_engine(cfg, params).generate([p], max_new_tokens=6)
        assert got == solo[0]


def test_oversubscribed_pool_completes(tiny):
    """More prompts than the KV pool can hold at once: the scheduler must
    run them through in shifts (blocks from finished sequences admit the
    rest) and still match sequential greedy outputs."""
    cfg, model, params = tiny
    rng = np.random.default_rng(1)
    prompts = [list(rng.integers(0, cfg.vocab_size, (10,)))
               for _ in range(6)]
    # each sequence needs ceil((10+8)/16)+1 = 3 blocks; pool of 8 fits
    # only ~2 concurrently (block 0 is scratch)
    engine = make_engine(cfg, params, num_blocks=8)
    free_before = engine.state.allocator.free_blocks
    outs = engine.generate(prompts, max_new_tokens=8)
    assert all(len(o) == 8 for o in outs)
    for p, got in zip(prompts, outs):
        solo = make_engine(cfg, params).generate([p], max_new_tokens=8)
        assert got == solo[0]
    # everything flushed at the end: the pool is back to its pre-run size
    assert engine.state.allocator.free_blocks == free_before


def test_impossible_request_raises(tiny):
    cfg, model, params = tiny
    engine = make_engine(cfg, params, num_blocks=3)
    prompt = list(np.random.default_rng(2).integers(0, 256, (40,)))
    with pytest.raises(SchedulingError):
        engine.generate([prompt], max_new_tokens=30)


def test_eos_frees_blocks_early(tiny):
    """A sequence hitting EOS flushes immediately; its blocks admit a
    pending prompt (observable: the run completes within a pool that
    could not hold all three at once)."""
    cfg, model, params = tiny
    rng = np.random.default_rng(3)
    prompts = [list(rng.integers(0, cfg.vocab_size, (10,)))
               for _ in range(3)]
    engine = make_engine(cfg, params, num_blocks=8)
    # pick eos = the greedy first token of prompt 0 so seq 0 retires fast
    probe = make_engine(cfg, params).generate([prompts[0]],
                                              max_new_tokens=1)
    eos = probe[0][0]
    outs = engine.generate(prompts, max_new_tokens=8, eos_token_id=eos)
    assert outs[0] == [eos]
    assert all(len(o) >= 1 for o in outs)


def test_top_p_sampling_valid_and_restricted(tiny):
    cfg, _model, params = tiny
    engine = make_engine(cfg, params)
    prompts = [[1, 2, 3]]
    # top_p=1.0 must be the exact default sampling path
    full = engine.generate(prompts, max_new_tokens=5, temperature=0.8,
                           seed=3)
    full_p1 = make_engine(cfg, params).generate(
        prompts, max_new_tokens=5, temperature=0.8, top_p=1.0, seed=3)
    assert list(full[0]) == list(full_p1[0])
    # a small nucleus must still produce in-vocab tokens
    # deterministically under a fixed seed
    out_a = engine.generate(prompts, max_new_tokens=5, temperature=0.8,
                            top_p=0.5, seed=3)
    out_b = make_engine(cfg, params).generate(
        prompts, max_new_tokens=5, temperature=0.8, top_p=0.5, seed=3)
    assert list(out_a[0]) == list(out_b[0])
    assert all(0 <= t < cfg.vocab_size for t in out_a[0])
    # near-greedy check: top_p tiny nucleus (only the argmax survives)
    greedy = make_engine(cfg, params).generate(prompts, max_new_tokens=5)
    nucleus = make_engine(cfg, params).generate(
        prompts, max_new_tokens=5, temperature=0.01, top_p=1e-9)
    assert list(nucleus[0]) == list(greedy[0])


def test_top_p_out_of_range_rejected(tiny):
    cfg, _model, params = tiny
    engine = make_engine(cfg, params)
    with pytest.raises(ValueError, match="top_p"):
        engine.generate([[1]], max_new_tokens=1, top_p=0.0)
