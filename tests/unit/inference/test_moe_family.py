"""MoE (Mixtral-family) paged inference.

Reference analog: the mixtral policy in
``deepspeed/inference/v2/engine_factory.py`` + the cutlass MoE module
(``modules/implementations/moe/cutlass_multi_gemm.py``) — here served by
``inference/model_moe.py``'s dropless grouped-GEMM path. The parity
oracle is the *training* Mixtral model running the same dropless math
(``models/mixtral.py`` with ``dropless=True``) — same param tree, so the
checkpoint drops straight into the engine.
"""

import jax
import numpy as np
import pytest

from hcache_deepspeed_tpu.inference import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig,
                                            build_hf_engine)
from hcache_deepspeed_tpu.inference.model_moe import PagedMoEModel
from hcache_deepspeed_tpu.models.mixtral import (MixtralConfig,
                                                 MixtralForCausalLM,
                                                 mixtral_tiny)


@pytest.fixture(scope="module")
def tiny_moe():
    cfg = mixtral_tiny(max_positions=128, use_flash=False, dropless=True)
    model = MixtralForCausalLM(cfg)
    batch = {"input_ids": np.zeros((1, 8), np.int32)}
    params = model.init(jax.random.PRNGKey(0), batch, train=False)["params"]
    return cfg, model, params


def make_engine(cfg, params, **over):
    kw = dict(state_manager={"max_tracked_sequences": 8,
                             "max_ragged_batch_size": 128,
                             "max_ragged_sequence_count": 4,
                             "max_context": 128},
              kv_cache={"block_size": 16, "num_blocks": 24,
                        "cache_dtype": "float32"})
    kw.update(over)
    return InferenceEngineV2(cfg, params,
                             config=RaggedInferenceEngineConfig(**kw))


def full_logits(model, params, tokens):
    out = model.apply({"params": params},
                      {"input_ids": np.asarray(tokens, np.int32)[None]},
                      train=False, return_logits=True)
    return np.asarray(out)[0]


class TestMoEPagedInference:

    def test_engine_selects_moe_model(self, tiny_moe):
        cfg, _, params = tiny_moe
        engine = make_engine(cfg, params)
        assert isinstance(engine.model, PagedMoEModel)

    def test_prefill_matches_full_forward(self, tiny_moe):
        cfg, model, params = tiny_moe
        engine = make_engine(cfg, params)
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, cfg.vocab_size, (13,))
        logits, latents = engine.put([7], [tokens])
        ref = full_logits(model, params, tokens)
        np.testing.assert_allclose(logits[0], ref[-1], atol=2e-2)
        assert latents[0].shape == (cfg.n_layer, 13, cfg.hidden_size)

    def test_incremental_decode_matches_full_forward(self, tiny_moe):
        cfg, model, params = tiny_moe
        engine = make_engine(cfg, params)
        rng = np.random.default_rng(1)
        tokens = list(rng.integers(0, cfg.vocab_size, (9,)))
        engine.put([1], [tokens])
        for _ in range(5):
            nxt = int(rng.integers(0, cfg.vocab_size))
            tokens.append(nxt)
            logits, _ = engine.put([1], [[nxt]])
            ref = full_logits(model, params, tokens)
            np.testing.assert_allclose(logits[0], ref[-1], atol=2e-2)

    def test_restore_equals_recompute(self, tiny_moe):
        """HCache restore works unchanged on the MoE family (restore
        replays only QKV — experts never run)."""
        cfg, model, params = tiny_moe
        rng = np.random.default_rng(4)
        prompt = list(rng.integers(0, cfg.vocab_size, (11,)))

        engine_a = make_engine(cfg, params)
        logits_a, latents = engine_a.put([1], [prompt])
        nxt = int(np.argmax(logits_a[0]))
        dec_a, _ = engine_a.put([1], [[nxt]])

        engine_b = make_engine(cfg, params)
        engine_b.restore_kv([1], [prompt], [latents[0]])
        dec_b, _ = engine_b.put([1], [[nxt]])
        np.testing.assert_allclose(dec_b[0], dec_a[0], atol=2e-2)

    def test_hf_factory_mixtral(self, tiny_moe):
        cfg, _, params = tiny_moe
        hf = {"model_type": "mixtral", "vocab_size": cfg.vocab_size,
              "hidden_size": cfg.hidden_size,
              "intermediate_size": cfg.intermediate_size,
              "num_hidden_layers": cfg.n_layer,
              "num_attention_heads": cfg.n_head,
              "num_key_value_heads": cfg.n_kv_head,
              "max_position_embeddings": 128,
              "num_local_experts": cfg.num_experts,
              "num_experts_per_tok": cfg.top_k,
              "torch_dtype": "float32"}
        engine = build_hf_engine(
            hf, params,
            engine_config=RaggedInferenceEngineConfig(
                state_manager={"max_tracked_sequences": 4,
                               "max_context": 128},
                kv_cache={"block_size": 16, "num_blocks": 24}))
        assert isinstance(engine.model, PagedMoEModel)
        logits, _ = engine.put([1], [[1, 2, 3]])
        assert np.isfinite(np.asarray(logits)).all()


class TestDroplessTrainingParity:
    """dropless=True training layer == capacity layer at generous capacity
    (no drops), and shares the same param tree."""

    def test_param_tree_identical(self):
        cfg_c = mixtral_tiny(use_flash=False)
        cfg_d = mixtral_tiny(use_flash=False, dropless=True)
        batch = {"input_ids": np.zeros((1, 8), np.int32)}
        pc = MixtralForCausalLM(cfg_c).init(
            jax.random.PRNGKey(0), batch, train=False)["params"]
        pd = MixtralForCausalLM(cfg_d).init(
            jax.random.PRNGKey(0), batch, train=False)["params"]
        sc = jax.tree.map(lambda x: (x.shape, x.dtype), pc)
        sd = jax.tree.map(lambda x: (x.shape, x.dtype), pd)
        assert sc == sd

    def test_dropless_equals_capacity_when_no_drops(self):
        """With capacity_factor = E no token can ever be dropped, so the
        capacity layer and the dropless layer compute the same function
        from the same params."""
        from hcache_deepspeed_tpu.moe.dropless import DroplessMOELayer
        from hcache_deepspeed_tpu.moe.layer import MOELayer

        E, d, f, k = 4, 16, 32, 2
        cap = MOELayer(num_experts=E, hidden_size=d, intermediate_size=f,
                       k=k, capacity_factor=float(E),
                       eval_capacity_factor=float(E), min_capacity=4)
        drop = DroplessMOELayer(num_experts=E, hidden_size=d,
                                intermediate_size=f, k=k)
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 8, d)).astype(np.float32)
        params = cap.init(jax.random.PRNGKey(0), x, train=True)
        out_c, aux_c = cap.apply(params, x, train=True)
        out_d, aux_d = drop.apply(params, x, train=True)
        np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_c),
                                   atol=1e-5)
        np.testing.assert_allclose(float(aux_d), float(aux_c), atol=1e-5)

    def test_dropless_trains(self):
        cfg = mixtral_tiny(use_flash=False, dropless=True)
        model = MixtralForCausalLM(cfg)
        rng = np.random.default_rng(0)
        batch = {"input_ids": rng.integers(0, cfg.vocab_size, (2, 16),
                                           dtype=np.int32)}
        params = model.init(jax.random.PRNGKey(0), batch, train=True)

        def loss_fn(p):
            return model.apply(p, batch, train=True)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        assert np.isfinite(float(loss))
        leaves = jax.tree.leaves(grads)
        assert all(np.isfinite(np.asarray(g)).all() for g in leaves)
        # the router specifically must receive gradient (a detached gate
        # would still leave expert/embed grads nonzero)
        wg_grad = grads["params"]["layers_0"]["mlp"]["moe"]["wg"]
        assert float(np.abs(np.asarray(wg_grad)).sum()) > 0
