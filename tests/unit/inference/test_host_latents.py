"""Coalesced layer-major host latent store (the restore payload
buffer): ndarray-contract parity with the np.concatenate accumulation
it replaces, amortized growth, dtype preservation (fp8 capture), and
drop-in use as a ``restore_kv`` payload."""

import numpy as np
import pytest

from hcache_deepspeed_tpu.inference.ragged.latents import HostLatentStore


def chunks(rng, n, L=2, H=4, dtype=np.float32):
    return [rng.standard_normal((L, t, H)).astype(dtype)
            for t in [5] + [1] * (n - 1)]       # prefill then decodes


def test_matches_concatenate_accumulation():
    rng = np.random.default_rng(0)
    parts = chunks(rng, 40)
    store = HostLatentStore()
    for p in parts:
        store.append(p)
    ref = np.concatenate(parts, axis=1)
    assert store.shape == ref.shape
    assert len(store) == ref.shape[1]
    np.testing.assert_array_equal(np.asarray(store), ref)
    np.testing.assert_array_equal(store.view(), ref)
    assert store.nbytes == ref.nbytes


def test_layer_major_contiguous_buffer():
    """The backing buffer is ONE C-contiguous [L, cap, H] array — a
    per-layer-chunk slice walks memory in shipping order."""
    store = HostLatentStore(np.ones((3, 4, 8), np.float32))
    store.append(np.ones((3, 1, 8), np.float32))
    assert store._buf.flags["C_CONTIGUOUS"]
    v = store.view()
    assert v.base is store._buf and v.shape == (3, 5, 8)


def test_growth_is_amortized_doubling():
    store = HostLatentStore()
    store.append(np.zeros((2, 3, 4), np.float32))
    caps = {store._buf.shape[1]}
    for _ in range(200):
        store.append(np.zeros((2, 1, 4), np.float32))
        caps.add(store._buf.shape[1])
    # 203 tokens via doubling from 16: few distinct capacities, not 200
    assert len(caps) <= 6 and len(store) == 203


def test_dtype_preserved_and_mismatch_rejected():
    import jax.numpy as jnp
    dt = np.dtype(jnp.float8_e4m3fn)
    store = HostLatentStore(np.zeros((2, 2, 4), dt))
    store.append(np.zeros((2, 1, 4), dt))
    assert store.dtype == dt and store.shape == (2, 3, 4)
    with pytest.raises(ValueError, match="does not match"):
        store.append(np.zeros((3, 1, 4), dt))      # wrong L
    with pytest.raises(ValueError, match="L, t, H"):
        store.append(np.zeros((4,), dt))
    with pytest.raises(ValueError, match="no view"):
        HostLatentStore().view()


def test_restore_payload_contract_with_sim_engine():
    """np.asarray(store) satisfies the [L, T, H] restore contract the
    engines check (shape[1] vs token count)."""
    from hcache_deepspeed_tpu.serving import SimulatedEngine
    eng = SimulatedEngine()
    tokens = list(range(10))
    _, lat = eng.put([7], [tokens])
    store = HostLatentStore(lat[0])
    eng.flush(7)
    eng.restore_kv([7], [tokens], [store])
    assert eng.state.get_sequence(7).seen_tokens == len(tokens)
