"""Tensor-parallel serving for the falcon (GQA) and phi trunks
(reference: TP sharding across v2 model implementations)."""

import jax
import numpy as np
import pytest

from hcache_deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
from hcache_deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
from hcache_deepspeed_tpu.models.falcon import (FalconForCausalLM,
                                                falcon_tiny)
from hcache_deepspeed_tpu.models.phi import PhiForCausalLM, phi_tiny
from hcache_deepspeed_tpu.parallel import topology as topo_mod


def _engine(cfg, params, topology=None):
    return InferenceEngineV2(
        cfg, params, topology=topology,
        config=RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 8,
                           "max_context": 128},
            kv_cache={"block_size": 16, "num_blocks": 24,
                      "cache_dtype": "float32"}))


@pytest.fixture
def tp_topo(eight_devices):
    topo = topo_mod.initialize_topology(
        topo_mod.TopologySpec(data=4, tensor=2))
    yield topo
    topo_mod.reset_topology()


def _init(model, cfg):
    batch = {"input_ids": np.zeros((1, 8), np.int32)}
    return model.init(jax.random.PRNGKey(0), batch,
                      train=False)["params"]


def _parity(cfg, model, params, tp_topo):
    ref = _engine(cfg, params)
    tp = _engine(cfg, params, topology=tp_topo)
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, cfg.vocab_size, (14,)).tolist()
    lr, _ = ref.put([1], [prompt])
    lt, _ = tp.put([1], [prompt])
    np.testing.assert_allclose(np.asarray(lr), np.asarray(lt), atol=2e-4)
    tok = int(np.argmax(np.asarray(lr)[0]))
    for _ in range(3):
        lr, _ = ref.put([1], [[tok]])
        lt, _ = tp.put([1], [[tok]])
        np.testing.assert_allclose(np.asarray(lr), np.asarray(lt),
                                   atol=2e-4)
        tok = int(np.argmax(np.asarray(lr)[0]))


def test_falcon_gqa_tp_parity(tp_topo):
    cfg = falcon_tiny(use_flash=False, n_head=4, n_kv_head=2)
    model = FalconForCausalLM(cfg)
    _parity(cfg, model, _init(model, cfg), tp_topo)


def test_falcon_mqa_tp_rejected(tp_topo):
    cfg = falcon_tiny(use_flash=False, n_head=4, n_kv_head=1)
    model = FalconForCausalLM(cfg)
    with pytest.raises(ValueError, match="divisible"):
        _engine(cfg, _init(model, cfg), topology=tp_topo)


def test_phi_tp_parity(tp_topo):
    cfg = phi_tiny(use_flash=False)
    model = PhiForCausalLM(cfg)
    _parity(cfg, model, _init(model, cfg), tp_topo)


def test_phi_head_bias_sharded(tp_topo):
    cfg = phi_tiny(use_flash=False)
    model = PhiForCausalLM(cfg)
    tp = _engine(cfg, _init(model, cfg), topology=tp_topo)
    head = tp.model.params["lm_head"]
    assert "tensor" in str(head["kernel"].sharding.spec)
    assert "tensor" in str(head["bias"].sharding.spec)
