"""Every bench.py config must build its model and trace its train step
abstractly (jax.eval_shape — no compile, no device) so a broken config
is caught here instead of burning a live-relay vetting window.
"""

import os
import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))
import bench  # noqa: E402


@pytest.mark.parametrize("name", sorted(bench.CONFIGS))
def test_config_traces(name):
    # bench.build_model is the SAME builder run_config measures with —
    # a private copy here once drifted (hardcoded n_layer=24) and
    # silently traced the wrong model for tiny-cpu-guard
    model, cfg, batch_size, seq = bench.build_model(name)
    batch = {"input_ids": jax.ShapeDtypeStruct((batch_size, seq),
                                               np.int32)}

    def init_and_loss(rng, batch):
        variables = model.init(rng, batch, train=False)
        loss = model.apply(variables, batch, train=True,
                           rngs={"dropout": rng})
        return loss

    out = jax.eval_shape(init_and_loss, jax.random.PRNGKey(0), batch)
    assert out.shape == ()


def test_candidates_are_configs():
    assert set(bench.CANDIDATES) <= set(bench.CONFIGS)
    # the last candidate must be the server-cache-proven one (round-2
    # workflow contract; see bench.py module docstring)
    assert bench.CANDIDATES[-1] == "350m-b8"


def test_stale_payload_carries_last_measurement(tmp_path, monkeypatch):
    """Dead-relay payloads must NOT promote the historical best to the
    top-level ``value`` (the driver scoreboard records it verbatim, so
    a zero-fresh-measurement round would masquerade as a best-ever run
    and mask regressions — ADVICE r5 high). The history rides under
    ``extra.last_measured`` with a top-level ``stale`` marker, and the
    exit code stays non-zero and distinct (3 = stale history exists,
    2 = nothing at all)."""
    state = {"best": {"value": 123.4, "mfu": 0.61, "vs_baseline": 1.13,
                      "config": "x", "utc": "2026-08-01T00:00:00Z"},
             "last": {"value": 100.0, "mfu": 0.50, "vs_baseline": 0.93,
                      "config": "y", "utc": "2026-08-02T00:00:00Z"}}
    p = tmp_path / "last.json"
    p.write_text(__import__("json").dumps(state))
    monkeypatch.setattr(bench, "_LAST_MEASURED_PATH", str(p))
    payload = bench._error_payload("relay down")
    assert payload["stale"] is True
    assert payload["value"] == 0.0            # never the stale best
    assert payload["vs_baseline"] == 0.0
    assert payload["stale_utc"] == "2026-08-01T00:00:00Z"
    assert payload["error"] == "relay down"
    assert payload["extra"]["last_measured"]["best"]["value"] == 123.4
    assert payload["extra"]["last_measured"]["last"]["value"] == 100.0
    assert bench._error_exit_code(payload) == 3
    # fresh payloads never set the key, so absence == fresh; and with
    # no history at all the exit code distinguishes that too
    monkeypatch.setattr(bench, "_LAST_MEASURED_PATH",
                        str(tmp_path / "missing.json"))
    payload = bench._error_payload("relay down")
    assert "stale" not in payload and payload["value"] == 0.0
    assert bench._error_exit_code(payload) == 2


def test_stale_payload_never_from_smoke(tmp_path, monkeypatch):
    """HDS_BENCH_TINY smoke runs must not transmit chip numbers."""
    state = {"best": {"value": 123.4, "mfu": 0.61, "vs_baseline": 1.13,
                      "config": "x", "utc": "u"}}
    p = tmp_path / "last.json"
    p.write_text(__import__("json").dumps(state))
    monkeypatch.setattr(bench, "_LAST_MEASURED_PATH", str(p))
    monkeypatch.setenv("HDS_BENCH_TINY", "1")
    payload = bench._error_payload("relay down")
    assert "stale" not in payload and payload["value"] == 0.0
