"""Every bench.py config must build its model and trace its train step
abstractly (jax.eval_shape — no compile, no device) so a broken config
is caught here instead of burning a live-relay vetting window.
"""

import os
import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))
import bench  # noqa: E402


def _model_for(name):
    spec = bench.CONFIGS[name]
    if spec.get("model") == "llama":
        from hcache_deepspeed_tpu.models.llama import (LlamaConfig,
                                                       LlamaForCausalLM)
        cfg = LlamaConfig(vocab_size=spec["vocab_size"],
                          hidden_size=spec["hidden"],
                          intermediate_size=spec["ffn"],
                          n_layer=spec["n_layer"], n_head=spec["n_head"],
                          n_kv_head=spec["n_head"],
                          max_positions=spec["seq"], dtype="bfloat16",
                          remat=spec.get("remat", False),
                          loss_chunk=spec["loss_chunk"])
        return LlamaForCausalLM(cfg), cfg, spec
    from hcache_deepspeed_tpu.models.gpt2 import (GPT2Config,
                                                  GPT2LMHeadModel)
    cfg = GPT2Config(n_layer=24, n_embd=1024, n_head=spec["n_head"],
                     n_positions=spec.get("seq", 1024),
                     vocab_size=spec["vocab_size"], dtype="bfloat16",
                     remat=spec.get("remat", False),
                     loss_chunk=spec["loss_chunk"],
                     flash_block_q=spec.get("block_q", 0),
                     flash_block_k=spec.get("block_k", 0))
    return GPT2LMHeadModel(cfg), cfg, spec


@pytest.mark.parametrize("name", sorted(bench.CONFIGS))
def test_config_traces(name):
    model, cfg, spec = _model_for(name)
    seq = spec.get("seq", 1024)
    batch = {"input_ids": jax.ShapeDtypeStruct((spec["batch"], seq),
                                               np.int32)}

    def init_and_loss(rng, batch):
        variables = model.init(rng, batch, train=False)
        loss = model.apply(variables, batch, train=True,
                           rngs={"dropout": rng})
        return loss

    out = jax.eval_shape(init_and_loss, jax.random.PRNGKey(0), batch)
    assert out.shape == ()


def test_candidates_are_configs():
    assert set(bench.CANDIDATES) <= set(bench.CONFIGS)
    # the last candidate must be the server-cache-proven one (round-2
    # workflow contract; see bench.py module docstring)
    assert bench.CANDIDATES[-1] == "350m-b8"
