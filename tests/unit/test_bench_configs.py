"""Every bench.py config must build its model and trace its train step
abstractly (jax.eval_shape — no compile, no device) so a broken config
is caught here instead of burning a live-relay vetting window.
"""

import os
import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))
import bench  # noqa: E402


@pytest.mark.parametrize("name", sorted(bench.CONFIGS))
def test_config_traces(name):
    # bench.build_model is the SAME builder run_config measures with —
    # a private copy here once drifted (hardcoded n_layer=24) and
    # silently traced the wrong model for tiny-cpu-guard
    model, cfg, batch_size, seq = bench.build_model(name)
    batch = {"input_ids": jax.ShapeDtypeStruct((batch_size, seq),
                                               np.int32)}

    def init_and_loss(rng, batch):
        variables = model.init(rng, batch, train=False)
        loss = model.apply(variables, batch, train=True,
                           rngs={"dropout": rng})
        return loss

    out = jax.eval_shape(init_and_loss, jax.random.PRNGKey(0), batch)
    assert out.shape == ()


def test_candidates_are_configs():
    assert set(bench.CANDIDATES) <= set(bench.CONFIGS)
    # the last candidate must be the server-cache-proven one (round-2
    # workflow contract; see bench.py module docstring)
    assert bench.CANDIDATES[-1] == "350m-b8"
