import pytest

from hcache_deepspeed_tpu.runtime.config import (HDSConfig, HDSConfigError,
                                                 load_config)


class TestConfig:
    def test_defaults(self):
        cfg = load_config({"train_batch_size": 8})
        assert cfg.zero_optimization.stage == 0
        assert not cfg.fp16.enabled and not cfg.bf16.enabled

    def test_reference_keys_parse(self):
        # a config written for the reference framework parses unchanged
        cfg = load_config({
            "train_batch_size": 32,
            "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": 2,
            "steps_per_print": 100,
            "optimizer": {"type": "Adam",
                          "params": {"lr": 1e-4, "betas": [0.9, 0.999],
                                     "eps": 1e-8, "weight_decay": 0.01}},
            "scheduler": {"type": "WarmupLR",
                          "params": {"warmup_min_lr": 0,
                                     "warmup_max_lr": 1e-4,
                                     "warmup_num_steps": 1000}},
            "gradient_clipping": 1.0,
            "fp16": {"enabled": False, "loss_scale": 0,
                     "initial_scale_power": 16},
            "bf16": {"enabled": True},
            "zero_optimization": {
                "stage": 2,
                "allgather_bucket_size": 5e8,
                "reduce_bucket_size": 5e8,
                "overlap_comm": True,
                "contiguous_gradients": True,
                "offload_optimizer": {"device": "cpu", "pin_memory": True},
            },
            "wall_clock_breakdown": False,
        })
        assert cfg.zero_optimization.stage == 2
        assert cfg.zero_optimization.offload_optimizer.device == "cpu"
        assert cfg.optimizer.params["betas"] == [0.9, 0.999]
        assert cfg.scheduler.type == "WarmupLR"

    def test_batch_trinity(self):
        cfg = load_config({"train_batch_size": 32,
                           "train_micro_batch_size_per_gpu": 2})
        train, micro, gas = cfg.resolve_batch_sizes(dp_world_size=4)
        assert (train, micro, gas) == (32, 2, 4)

    def test_batch_trinity_infer_train(self):
        cfg = load_config({"train_micro_batch_size_per_gpu": 2,
                           "gradient_accumulation_steps": 3})
        train, micro, gas = cfg.resolve_batch_sizes(dp_world_size=4)
        assert train == 24

    def test_batch_trinity_inconsistent(self):
        cfg = load_config({"train_batch_size": 10,
                           "train_micro_batch_size_per_gpu": 4,
                           "gradient_accumulation_steps": 1})
        with pytest.raises(HDSConfigError):
            cfg.resolve_batch_sizes(dp_world_size=4)

    def test_fp16_bf16_conflict(self):
        with pytest.raises(HDSConfigError):
            load_config({"train_batch_size": 8, "fp16": {"enabled": True},
                         "bf16": {"enabled": True}})

    def test_unknown_key_tolerated(self):
        cfg = load_config({"train_batch_size": 8,
                           "some_future_key": {"x": 1}})
        assert cfg.train_batch_size == 8
