"""Golden-schema walk of the committed perf evidence.

Walks every committed root ``*.json`` / ``*.jsonl`` artifact and
asserts it (a) classifies into a registry family, (b) parses under
that family's schema, and (c) is represented in the committed
``PERF_TRAJECTORY.json`` — or is explicitly allowlisted in
``perf/KNOWN_UNINDEXED`` with a justification. The allowlist goal is
EMPTY; a future PR adding an artifact family without a schema fails
here, which is the point.
"""

import json
import os

import pytest

from hcache_deepspeed_tpu.perf import (INDEX_NAME, build_index,
                                       classify, load_allowlist,
                                       load_index, parse_artifact)
from hcache_deepspeed_tpu.perf.registry import iter_artifact_names

ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", ".."))


def _root_artifacts():
    return [n for n in iter_artifact_names(ROOT)
            if n.endswith((".json", ".jsonl"))]


def test_repo_root_sane():
    assert os.path.exists(os.path.join(ROOT, "bench.py"))
    assert _root_artifacts(), "no committed artifacts found?"


@pytest.mark.parametrize("name", _root_artifacts())
def test_every_root_artifact_classifies_and_parses(name):
    allow = load_allowlist()
    fam = classify(name)
    if fam is None:
        assert name in allow, (
            f"{name} matches no registry family and is not "
            "allowlisted in perf/KNOWN_UNINDEXED — declare a schema "
            "in perf/schemas.py")
        assert allow[name], (
            f"{name} is allowlisted without a justification")
        return
    parsed = parse_artifact(os.path.join(ROOT, name), name)
    assert parsed.status in ("ok", "empty", "meta"), \
        f"{name}: {parsed.status} ({parsed.note})"
    # a non-empty data artifact must yield at least one indexable
    # point OR be a declared meta family
    if parsed.status == "ok":
        assert parsed.points or parsed.family in ("chip-log",), \
            f"{name} parsed but yielded no metric points"


def test_allowlist_is_empty_goal():
    """The allowlist is a debt ledger: every entry must name a file
    that actually exists (no stale entries) and carry a reason. The
    committed goal state is empty."""
    allow = load_allowlist()
    for name, why in allow.items():
        assert why, f"allowlist entry {name} has no justification"
        assert os.path.exists(os.path.join(ROOT, name)), \
            f"allowlist entry {name} names a nonexistent file"
    assert allow == {}, (
        "perf/KNOWN_UNINDEXED should stay empty — declare schemas "
        f"instead of allowlisting: {sorted(allow)}")


def test_committed_index_exists_and_covers_every_artifact():
    index = load_index(root=ROOT)
    assert index["version"] == 1
    indexed = {a["file"] for a in index["artifacts"]}
    for name in _root_artifacts():
        assert name in indexed, (
            f"{name} missing from committed {INDEX_NAME} — rerun "
            "`python -m hcache_deepspeed_tpu.perf index --git`")
    # no artifact landed in an error/unindexed state
    bad = [a for a in index["artifacts"]
           if a["status"] in ("error", "unindexed")
           and not a.get("allowlisted")]
    assert not bad, f"broken/unindexed committed artifacts: {bad}"


def test_committed_index_matches_fresh_rebuild():
    """The committed series must equal a fresh rebuild of the same
    tree (metric names, point counts, values) — a PR that changes
    artifacts or schemas without re-indexing fails here."""
    committed = load_index(root=ROOT)
    fresh = build_index(ROOT)
    assert sorted(fresh["series"]) == sorted(committed["series"]), (
        "series set drifted — rerun the perf index CLI")
    for metric, rows in fresh["series"].items():
        crows = committed["series"][metric]
        assert len(rows) == len(crows), f"{metric}: point count drift"
        assert [r["value"] for r in rows] == \
            [r["value"] for r in crows], f"{metric}: values drift"
    # headline block agrees on values (tolerances come from code)
    for metric, head in fresh["headline"].items():
        assert metric in committed["headline"], metric
        assert committed["headline"][metric]["value"] == \
            head["value"], f"headline {metric} drifted"


def test_index_freshness_block_reflects_stale_convention():
    """The wedged-relay condition is a queryable gauge: the committed
    index carries the last chip measurement timestamp and its age
    (bench.py's dead-relay ``stale`` convention, ROADMAP item 5)."""
    index = load_index(root=ROOT)
    fr = index["freshness"]
    assert fr["last_chip_measurement_utc"], \
        "no chip measurement timestamp indexed"
    assert fr["staleness_days"] is not None
    # relay wedged since 2026-08-01/02; the index must say so rather
    # than pretend freshness
    assert fr["staleness_days"] >= 0.0
    # staleness also surfaces as a per-point field on utc-carrying
    # series
    series = index["series"]
    assert any("staleness_days" in rec
               for rows in series.values() for rec in rows)


def test_empty_artifacts_are_visible_not_silent():
    """Zero-byte artifacts (interrupted chip sessions) index with
    status=empty — never dropped."""
    index = load_index(root=ROOT)
    by_file = {a["file"]: a for a in index["artifacts"]}
    empties = [n for n in _root_artifacts()
               if os.path.getsize(os.path.join(ROOT, n)) == 0]
    for name in empties:
        assert by_file[name]["status"] == "empty", name


def test_jsonl_rows_all_parse_or_are_log_lines():
    """Every line in every committed JSONL either parses as JSON or
    is a recognizable log line — no half-written JSON rows."""
    for name in _root_artifacts():
        if not name.endswith(".jsonl"):
            continue
        with open(os.path.join(ROOT, name), encoding="utf-8",
                  errors="replace") as fh:
            for i, line in enumerate(fh, 1):
                line = line.strip()
                if not line:
                    continue
                if line.startswith("{"):
                    try:
                        json.loads(line)
                    except json.JSONDecodeError as exc:
                        raise AssertionError(
                            f"{name}:{i}: corrupt JSON row: "
                            f"{exc}") from exc
                else:
                    assert line.startswith(("[", "WARNING", "INFO",
                                            "ERROR", "#")), \
                        f"{name}:{i}: unrecognizable line {line[:60]!r}"
