"""Regression sentinel behavior: the gate must demonstrably fail on a
synthetically regressed artifact, pass on faithful/improved ones, and
never cross-compare different workloads."""

import json
import os

from hcache_deepspeed_tpu.perf import (MetricPoint, check_artifact,
                                       check_headline, check_points,
                                       freshness_alarm, load_index,
                                       regressions, self_check_rows,
                                       self_test)
from hcache_deepspeed_tpu.perf.registry import build_index

ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", ".."))


def _committed_index():
    return load_index(root=ROOT)


def test_self_test_gate_trips():
    assert self_test()


def test_synthetically_regressed_serve_loop_fails(tmp_path):
    """Take the committed SERVE_LOOP summary, multiply TTFT p99 by
    10x and halve throughput, write it as a fresh artifact, and the
    gate must fail it."""
    index = _committed_index()
    src = os.path.join(ROOT, "SERVE_LOOP.jsonl")
    rows = [json.loads(line) for line in open(src)
            if line.strip().startswith("{")]
    summary = next(r for r in rows
                   if r.get("phase") == "serve-loop-summary")
    bad = dict(summary)
    bad["ttft_s"] = dict(summary["ttft_s"],
                         p99=summary["ttft_s"]["p99"] * 10)
    bad["gen_tokens_per_sec"] = summary["gen_tokens_per_sec"] * 0.4
    art = tmp_path / "SERVE_LOOP.jsonl"
    art.write_text(json.dumps(bad) + "\n")
    verdicts = check_artifact(str(art), index)
    regs = {v.metric for v in regressions(verdicts)}
    assert "serve_loop.ttft_s_p99" in regs
    assert "serve_loop.gen_tokens_per_sec" in regs


def test_faithful_copy_passes(tmp_path):
    index = _committed_index()
    src = os.path.join(ROOT, "SERVE_LOOP.jsonl")
    art = tmp_path / "SERVE_LOOP.jsonl"
    art.write_text(open(src).read())
    assert not regressions(check_artifact(str(art), index))


def test_regressed_zero_overlap_boolean_fails(tmp_path):
    """Parity booleans gate at zero tolerance: bitwise_parity=false
    in a fresh ZERO_OVERLAP artifact is a regression."""
    index = _committed_index()
    row = {"phase": "summary", "bitwise_parity": False,
           "gather_overlap_ratio_on": 0.375,
           "qrs_wire_fraction_of_fp32": 0.3292,
           "native_async_pairs": 0, "prefetch_on_gather_pairs": 6,
           "utc": "2026-08-04T00:00:00Z"}
    art = tmp_path / "ZERO_OVERLAP.jsonl"
    art.write_text(json.dumps(row) + "\n")
    regs = {v.metric
            for v in regressions(check_artifact(str(art), index))}
    assert "zero_overlap.bitwise_parity" in regs


def test_improvement_is_not_a_regression():
    index = _committed_index()
    verdicts = check_points(
        [MetricPoint("zero_overlap.gather_overlap_ratio", 0.9,
                     "NEW.jsonl")], index)
    assert not regressions(verdicts)
    assert any(v.status == "improved" for v in verdicts)


def test_different_config_is_not_compared():
    """A 7B-layer vet point must not 'regress' the 350m headline —
    like-for-like only."""
    index = _committed_index()
    verdicts = check_points(
        [MetricPoint("train.tokens_per_sec_per_chip", 14000.0,
                     "VET_X.json",
                     tags={"config": "350m-hd128-lchunk-seq16k-b1"})],
        index)
    assert not verdicts, \
        "different-config point produced a verdict"


def test_headline_mode_detects_evidence_tampering(tmp_path):
    """Repo mode: rebuilding the index over a tree whose best evidence
    got worse must fail against the committed baseline."""
    baseline = _committed_index()
    fresh = build_index(ROOT)
    ok = check_headline(fresh, baseline)
    assert not regressions(ok), \
        "pristine tree must pass its own committed baseline"
    # tamper: drop the best zero-overlap ratio in the fresh headline
    fresh["headline"]["zero_overlap.gather_overlap_ratio"]["value"] \
        = 0.1
    regs = regressions(check_headline(fresh, baseline))
    assert any(v.metric == "zero_overlap.gather_overlap_ratio"
               for v in regs)
    # tamper harder: the metric vanishes entirely
    del fresh["headline"]["zero_overlap.gather_overlap_ratio"]
    regs = regressions(check_headline(fresh, baseline))
    assert any(v.metric == "zero_overlap.gather_overlap_ratio"
               for v in regs)


def test_self_check_rows_roundtrip():
    """The bench hook: within-tolerance rows produce ok=True, a
    regressed row is recorded in the artifact-bound verdict."""
    rows = [{"phase": "chaos-summary", "deterministic": True,
             "invariants_ok": True, "violations": []}]
    out = self_check_rows("CHAOS_SERVE.jsonl", rows, root=ROOT)
    assert out["phase"] == "perf-check"
    assert out.get("ok") is True, out
    bad = [{"phase": "chaos-summary", "deterministic": False,
            "invariants_ok": True, "violations": []}]
    out = self_check_rows("CHAOS_SERVE.jsonl", bad, root=ROOT)
    assert out.get("ok") is False
    assert any(r["metric"] == "chaos.deterministic"
               for r in out["regressions"])


def test_freshness_gauge_is_queryable():
    """ROADMAP item 5's wedged-relay condition as a gauge: the
    committed index always carries a timestamped chip measurement and
    its age; the alarm fires on a synthetic stale index and stays
    quiet on a fresh one (no dependence on the relay's current
    state)."""
    index = _committed_index()
    fr = index["freshness"]
    assert fr["last_chip_measurement_utc"]
    assert fr["staleness_days"] is not None and \
        fr["staleness_days"] >= 0.0
    stale = {"freshness": {"last_chip_measurement_utc":
                           "2026-08-01T00:00:00Z",
                           "staleness_days": 3.4, "stale": True}}
    assert freshness_alarm(stale, max_age_days=2.0)
    fresh = {"freshness": {"last_chip_measurement_utc":
                           "2026-08-04T00:00:00Z",
                           "staleness_days": 0.1, "stale": False}}
    assert freshness_alarm(fresh, max_age_days=2.0) is None
    assert freshness_alarm({}, max_age_days=2.0)   # nothing indexed


def test_cli_check_self_test_and_lint():
    from hcache_deepspeed_tpu.perf.__main__ import main
    assert main(["check", "--self-test"]) == 0
    assert main(["--root", ROOT, "lint"]) == 0


def test_lint_catches_schemaless_artifact_literal(tmp_path):
    """perf lint fails when source writes an artifact name the
    registry has no schema for."""
    from hcache_deepspeed_tpu.perf.registry import lint_sources
    root = tmp_path / "repo"
    (root / "hcache_deepspeed_tpu").mkdir(parents=True)
    (root / "bench.py").write_text(
        'OUT = "TOTALLY_NEW_EVIDENCE.jsonl"\n')
    violations = lint_sources(root=str(root))
    assert violations and "TOTALLY_NEW_EVIDENCE.jsonl" in \
        violations[0]
    # a schema'd name lints clean
    (root / "bench.py").write_text('OUT = "ZERO_OVERLAP.jsonl"\n')
    assert lint_sources(root=str(root)) == []
