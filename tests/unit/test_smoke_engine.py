"""Engine smoke for the fast tier: one REAL end-to-end
init → train (loss falls) → checkpoint round trip → post-restore step,
on a single-device mesh so the compile stays in smoke-tier budget. The
multi-device/ZeRO/parallelism engine coverage lives in the `slow`
tier (runtime/test_engine.py and friends)."""

import jax
import numpy as np

import hcache_deepspeed_tpu as hds
from hcache_deepspeed_tpu.models.gpt2 import GPT2LMHeadModel, gpt2_tiny
from hcache_deepspeed_tpu.parallel import topology as topo_mod


def test_train_checkpoint_resume_single_device(tmp_path):
    topo = topo_mod.initialize_topology(
        topo_mod.TopologySpec(data=1), devices=jax.devices()[:1])
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 256, (4, 32), np.int32)}
    engine, _, _, _ = hds.initialize(
        model=GPT2LMHeadModel(gpt2_tiny()), topology=topo,
        config={"train_batch_size": 4,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}},
        example_batch=batch)
    losses = [float(engine.train_batch(batch=batch)) for _ in range(4)]
    assert losses[-1] < losses[0]
    engine.save_checkpoint(str(tmp_path), tag="smoke")
    engine.load_checkpoint(str(tmp_path), tag="smoke")
    post = float(engine.train_batch(batch=batch))
    assert np.isfinite(post) and post < losses[0]
