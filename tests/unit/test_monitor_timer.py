"""Satellite regressions for the observability PR: CSVMonitor handle
caching, InMemoryMonitor bounded deque, comms log_summary through the
monitor sink, ThroughputTimer event emission, and _Timer.elapsed
semantics."""

import csv
import gc
from collections import deque

from hcache_deepspeed_tpu.comm.comms_logging import CommsLogger
from hcache_deepspeed_tpu.monitor.monitor import (CSVMonitor,
                                                  InMemoryMonitor,
                                                  Monitor)
from hcache_deepspeed_tpu.utils.timer import ThroughputTimer, _Timer


class _CSVCfg:
    enabled = True
    output_path = None
    job_name = "job"


# ------------------------------------------------------------------ #
# CSVMonitor: cached handles instead of reopen-per-event
# ------------------------------------------------------------------ #
def test_csv_monitor_caches_file_handles(tmp_path):
    cfg = _CSVCfg()
    cfg.output_path = str(tmp_path)
    mon = CSVMonitor(cfg)
    for step in range(5):
        mon.write_events([("Train/loss", 0.5 - step * 0.01, step),
                          ("Train/lr", 1e-3, step)])
    # one cached handle per label, not one open() per event
    assert set(mon._files) == {"Train/loss", "Train/lr"}
    mon.flush()
    path = tmp_path / "job" / "Train_loss.csv"
    rows = list(csv.reader(path.open()))
    assert rows[0] == ["step", "Train/loss"]
    assert len(rows) == 6 and rows[1][0] == "0" and rows[5][0] == "4"
    mon.close()
    assert mon._files == {}
    # close is idempotent and __del__-safe
    mon.close()
    del mon
    gc.collect()


def test_csv_monitor_append_resumes_without_second_header(tmp_path):
    cfg = _CSVCfg()
    cfg.output_path = str(tmp_path)
    mon = CSVMonitor(cfg)
    mon.write_events([("m", 1.0, 0)], flush=True)
    mon.close()
    mon2 = CSVMonitor(cfg)
    mon2.write_events([("m", 2.0, 1)], flush=True)
    rows = list(csv.reader((tmp_path / "job" / "m.csv").open()))
    assert rows == [["step", "m"], ["0", "1.0"], ["1", "2.0"]]
    mon2.close()


# ------------------------------------------------------------------ #
# InMemoryMonitor: bounded deque, O(1) eviction
# ------------------------------------------------------------------ #
def test_in_memory_monitor_bounded_deque():
    mon = InMemoryMonitor(capacity=4)
    assert isinstance(mon.events, deque)
    mon.write_events([("a", float(i), i) for i in range(10)])
    assert len(mon.events) == 4
    assert [step for _, _, step in mon.events] == [6, 7, 8, 9]
    assert mon.latest["a"] == (9.0, 9)


# ------------------------------------------------------------------ #
# comms log_summary -> monitor sink
# ------------------------------------------------------------------ #
def test_comms_log_summary_routes_through_monitor():
    logger = CommsLogger(enabled=True)
    logger.append("all_reduce", ("data",), 1024)
    logger.append("all_reduce", ("data",), 1024)
    logger.append("all_gather", (), 256)
    mon = InMemoryMonitor()
    logger.log_summary(monitor=mon, step=7)
    got = {label: (value, step) for label, value, step in mon.events}
    assert got["CommsSummary/all_reduce@data/count"] == (2.0, 7)
    assert got["CommsSummary/all_reduce@data/bytes"] == (2048.0, 7)
    assert got["CommsSummary/all_gather@world/bytes"] == (256.0, 7)


def test_comms_append_emits_trace_instants():
    from hcache_deepspeed_tpu.telemetry.tracer import get_tracer
    tracer = get_tracer()
    was = tracer.enabled
    tracer.configure(enabled=True, xla=False)
    tracer.clear()
    try:
        logger = CommsLogger(enabled=True)
        logger.append("reduce_scatter", ("data", "tensor"), 4096)
    finally:
        tracer.configure(enabled=was)
    (ev,) = [e for e in tracer.events()
             if e["name"] == "comm.reduce_scatter"]
    assert ev["ph"] == "i"
    assert ev["args"] == {"bytes": 4096, "axes": "data,tensor"}


# ------------------------------------------------------------------ #
# timers
# ------------------------------------------------------------------ #
def test_timer_elapsed_no_reset_keeps_running_count():
    t = _Timer("t")
    for _ in range(3):
        t.start()
        t.stop()
    count_before = t.count
    total = t.elapsed(reset=False)
    # regression: reset=False must clear NEITHER the accumulator NOR
    # the running count
    assert t.count == count_before == 3
    assert t.elapsed(reset=False) == total
    assert t.mean() == total / 3
    t.elapsed(reset=True)
    assert t.count == 0 and t.elapsed_ == 0.0


def test_throughput_timer_emits_tokens_and_samples_per_sec():
    mon = InMemoryMonitor()
    tt = ThroughputTimer(batch_size=4, start_step=1, steps_per_output=0,
                         monitor=mon, emit_events=True)
    for _ in range(3):
        tt.start()
        tt.stop(report_speed=False, tokens=128)
    labels = [label for label, _, _ in mon.events]
    assert labels.count("Train/samples_per_sec") == 3
    assert labels.count("Train/tokens_per_sec") == 3
    steps = [step for label, _, step in mon.events
             if label == "Train/tokens_per_sec"]
    assert steps == [1, 2, 3]
    assert all(v > 0 for _, v, _ in mon.events)


def test_throughput_timer_silent_without_monitor():
    tt = ThroughputTimer(batch_size=4, start_step=1)
    tt.start()
    tt.stop(tokens=128)          # must not raise without a monitor
    assert tt.global_step_count == 1


# ------------------------------------------------------------------ #
# Monitor.flush contract: explicit no-op default on the base class,
# buffering sinks override, fan-out callers can flush deterministically
# ------------------------------------------------------------------ #
def test_base_monitor_flush_is_explicit_noop():
    mon = Monitor(config=None)
    assert mon.flush() is None          # present and safe on the base
    assert InMemoryMonitor().flush() is None


def test_csv_monitor_flush_makes_events_durable(tmp_path):
    cfg = _CSVCfg()
    cfg.output_path = str(tmp_path)
    mon = CSVMonitor(cfg)
    mon.write_events([("serving/ttft_s/p50", 0.2, 1)])
    mon.flush()
    path = tmp_path / "job" / "serving_ttft_s_p50.csv"
    rows = list(csv.reader(path.open()))
    assert rows[-1] == ["1", "0.2"]
    mon.close()


def test_serving_metrics_emit_flush_reaches_sink(tmp_path):
    """ServingMetrics.emit(..., flush=True) drives the contract end to
    end — the deterministic end-of-trace flush run_trace performs."""
    from hcache_deepspeed_tpu.serving.metrics import ServingMetrics

    class FlushSpy(InMemoryMonitor):
        def __init__(self):
            super().__init__()
            self.flushes = 0

        def flush(self):
            self.flushes += 1

    spy = FlushSpy()
    m = ServingMetrics()
    m.emit(spy, step=1)
    assert spy.flushes == 0
    m.emit(spy, step=2, flush=True)
    assert spy.flushes == 1
