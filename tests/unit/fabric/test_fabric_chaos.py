"""Fabric chaos gate: a literal kill-a-process over the process
transport, recovered from the survivors' view with never-dropped
accounting. (The FABRIC_SERVE artifact additionally gates 2-run digest
determinism; here one canonical run keeps the suite fast — spawning a
worker fleet costs real seconds.)"""

import pytest

from hcache_deepspeed_tpu.resilience import run_fabric_chaos

pytestmark = pytest.mark.chaos


def test_fabric_chaos_kill_a_process_recovers():
    r = run_fabric_chaos(seed=0)
    assert r.ok, r.violations
    inv = r.invariants
    # the kill was literal and observed through the liveness pass
    assert r.wire["kills"] == 1
    assert inv["counters"]["replica_crashes"] >= 1
    assert inv["replica_states"][str(r.victim)] == "DEAD"
    # never dropped: every request reached exactly one terminal state
    assert set(inv["terminal_states"]) <= {"DONE", "REJECTED",
                                           "FAILED"}
    assert inv["done_after"] > inv["done_before_kill"]
    # real bytes crossed real sockets, and the wire was measured
    assert r.wire["deliveries"] > 0
    assert r.wire["measured_wire_bytes_per_s"] > 0
    assert r.wire["bootstrap_mismatches"] == 0
    # causal traces stayed connected across the crash
    assert inv["trace"]["connected"]
