"""Fabric wire frame: versioned binary codec round-trip, int8+scales
latent segment parity with ``ops.quantizer.reference_quantize``,
forward-compatible header handling, typed version rejection, and the
golden fixture pinning the v1 bytes."""

import json
import os
import struct

import numpy as np
import pytest

from hcache_deepspeed_tpu.fabric import (FRAME_VERSION, FrameError,
                                         FrameVersionError,
                                         decode_frame, dequantize_q8,
                                         encode_frame, quantize_q8)
from hcache_deepspeed_tpu.fabric.frame import _PREAMBLE, MAGIC

GOLDEN = os.path.join(os.path.dirname(__file__),
                      "golden_frame_v1.bin")
GOLDEN_TELEMETRY = os.path.join(os.path.dirname(__file__),
                                "golden_telemetry_v1.bin")


def golden_frame_bytes() -> bytes:
    """The fixture's logical content, re-encoded from scratch. The
    golden test asserts these bytes equal the committed file — i.e.
    the encoder is a pure function of its inputs and the v1 format
    has not drifted."""
    rng = np.random.default_rng(1234)
    return encode_frame(
        "migration",
        header={"uid": 42, "src": 1, "dst": 2, "reason": "rebalance",
                "tokens": 11,
                "trace": {"v": 1, "trace_id": "cafe", "uid": 42,
                          "hops": 1, "baggage": {"tenant": "gold"}},
                "prefix_tokens": None,
                "future_field_decoders_must_keep": {"x": [1, 2]}},
        arrays={"latents": rng.standard_normal(
                    (2, 11, 4)).astype(np.float32),
                "tokens": np.arange(11, dtype=np.int32)},
        q8={"latents_q8": rng.standard_normal(
                (2, 11, 4)).astype(np.float32)},
        q8_group=16)


def golden_telemetry_bytes() -> bytes:
    """A representative ``telemetry_ok`` harvest reply — the new
    header-only frame kind the supervision channel speaks. Everything
    rides in the JSON header (no array segments), so the golden file
    pins the exact canonical-JSON byte layout a v1 worker replies
    with."""
    return encode_frame(
        "telemetry_ok",
        header={"replica": 1, "v": 1,
                "now_us": 1234.5, "t_send_us": 1200.25,
                "events": [
                    {"ph": "X", "name": "fabric.migration",
                     "ts": 10.0, "dur": 2.5, "pid": 0, "tid": 1,
                     "args": {"replica": 1, "uid": 42}},
                    {"ph": "i", "name": "fabric.migrate_in",
                     "ts": 11.0, "pid": 0, "tid": 1,
                     "args": {"uid": 42, "replica": 1}}],
                "dropped": 0,
                "thread_names": {"1": "fabric-worker"},
                "counters": {"frames": 3, "bytes_in": 4096,
                             "bytes_out": 2048, "q8_segments": 1,
                             "decode_seconds": 0.001,
                             "encode_seconds": 0.002,
                             "migrations": 1, "forwards": 0,
                             "peer_connections": 0},
                "metrics": [{"name": "hds_fabric_worker_frames",
                             "type": "counter",
                             "labels": {"replica": "1"},
                             "value": 3.0}],
                "rss_max_bytes": 104857600,
                "future_field_decoders_must_keep": {"x": [1]}})


# ------------------------------------------------------------------ #
# raw round trip: bit-exactness is the process-parity foundation
# ------------------------------------------------------------------ #
@pytest.mark.parametrize("dtype", [np.float32, np.float16, np.int8,
                                   np.int32, np.uint8, np.int64])
def test_raw_round_trip_bit_exact(dtype):
    rng = np.random.default_rng(7)
    a = (rng.standard_normal((3, 5, 4)) * 100).astype(dtype)
    f = decode_frame(encode_frame("t", {"k": 1}, arrays={"a": a}))
    assert f.kind == "t" and f.header["k"] == 1
    assert f.arrays["a"].dtype == a.dtype
    assert f.arrays["a"].tobytes() == a.tobytes()
    assert f.meta["a"]["enc"] == "raw"


def test_round_trip_multiple_segments_and_empty_frame():
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    b = np.arange(4, dtype=np.int8)
    f = decode_frame(encode_frame("multi", {}, arrays={"b": b, "a": a}))
    assert set(f.arrays) == {"a", "b"}
    assert np.array_equal(f.arrays["a"], a)
    assert np.array_equal(f.arrays["b"], b)
    g = decode_frame(encode_frame("empty", {"only": "header"}))
    assert g.arrays == {} and g.header["only"] == "header"


def test_encode_is_deterministic_and_key_order_free():
    a = np.arange(8, dtype=np.float32)
    one = encode_frame("d", {"x": 1, "y": 2}, arrays={"a": a})
    two = encode_frame("d", {"y": 2, "x": 1}, arrays={"a": a})
    assert one == two


# ------------------------------------------------------------------ #
# q8 segments: the int8+scales latent format on the wire
# ------------------------------------------------------------------ #
def test_quantize_q8_matches_reference_quantize():
    jq = pytest.importorskip("jax.numpy")
    from hcache_deepspeed_tpu.ops.quantizer import reference_quantize
    rng = np.random.default_rng(3)
    x = rng.standard_normal((2, 7, 5)).astype(np.float32)
    q, s, shape, n = quantize_q8(x, group_size=16)
    rq, rs, rshape, rn = reference_quantize(jq.asarray(x),
                                            group_size=16)
    assert np.array_equal(q, np.asarray(rq))
    assert np.array_equal(s, np.asarray(rs))
    assert tuple(shape) == tuple(rshape) and n == rn


def test_q8_round_trip_error_bounded_and_zero_group_exact():
    rng = np.random.default_rng(11)
    x = rng.standard_normal((4, 64)).astype(np.float32)
    x[1, :] = 0.0                       # all-zero group: scale -> 1.0
    q, s, shape, n = quantize_q8(x, group_size=64)
    back = dequantize_q8(q, s, shape, n)
    assert back.shape == x.shape
    assert np.array_equal(back[1], x[1])
    # absmax grouping bounds the per-element error by scale/2
    assert np.all(np.abs(back - x) <= s.reshape(4, 1) / 2 + 1e-7)


def test_q8_segment_through_frame_matches_direct_quantize():
    rng = np.random.default_rng(5)
    x = rng.standard_normal((2, 9, 4)).astype(np.float32)
    f = decode_frame(encode_frame("q", q8={"x": x}, q8_group=16))
    q, s, shape, n = quantize_q8(x, group_size=16)
    assert np.array_equal(f.arrays["x"],
                          dequantize_q8(q, s, shape, n))
    assert f.meta["x"]["enc"] == "q8"
    assert f.meta["x"]["group"] == 16


# ------------------------------------------------------------------ #
# error surface + forward compatibility
# ------------------------------------------------------------------ #
def test_reserved_segments_header_key_rejected():
    with pytest.raises(FrameError):
        encode_frame("t", {"_segments": []})


def test_bad_magic_and_truncations_raise_typed_errors():
    buf = encode_frame("t", {"a": 1},
                       arrays={"x": np.arange(4, dtype=np.float32)})
    with pytest.raises(FrameError):
        decode_frame(b"NOPE" + buf[4:])
    with pytest.raises(FrameError):
        decode_frame(buf[:3])                  # inside preamble
    with pytest.raises(FrameError):
        decode_frame(buf[:_PREAMBLE.size + 2])  # inside header
    with pytest.raises(FrameError):
        decode_frame(buf[:-1])                 # inside segment


def test_unknown_version_raises_frame_version_error():
    buf = encode_frame("t", {"a": 1}, version=FRAME_VERSION + 1)
    with pytest.raises(FrameVersionError):
        decode_frame(buf)
    # the typed error is still a FrameError (and a ValueError), so
    # blanket frame handling catches it
    assert issubclass(FrameVersionError, FrameError)
    assert issubclass(FrameError, ValueError)


def test_unknown_header_fields_are_tolerated_and_preserved():
    buf = encode_frame("t", {"known": 1,
                             "from_the_future": {"deep": [1, 2]}})
    f = decode_frame(buf)
    assert f.header["from_the_future"] == {"deep": [1, 2]}


def test_unknown_segment_encoding_rejected():
    # hand-craft a frame whose descriptor names an encoding this
    # build does not speak
    hdr = json.dumps({"kind": "t", "_segments": [
        {"name": "x", "enc": "zstd-of-the-future", "nbytes": 0}]},
        sort_keys=True, separators=(",", ":")).encode()
    buf = _PREAMBLE.pack(MAGIC, FRAME_VERSION, len(hdr)) + hdr
    with pytest.raises(FrameError):
        decode_frame(buf)


def test_header_must_be_json_object():
    hdr = b"[1,2,3]"
    buf = _PREAMBLE.pack(MAGIC, FRAME_VERSION, len(hdr)) + hdr
    with pytest.raises(FrameError):
        decode_frame(buf)


# ------------------------------------------------------------------ #
# golden fixture: the committed v1 bytes
# ------------------------------------------------------------------ #
def test_golden_frame_bytes_are_stable():
    with open(GOLDEN, "rb") as fh:
        committed = fh.read()
    assert golden_frame_bytes() == committed, \
        "frame encoder output drifted from the committed v1 fixture " \
        "— bump FRAME_VERSION instead of silently changing the format"


def test_golden_frame_decodes_with_pinned_content():
    with open(GOLDEN, "rb") as fh:
        f = decode_frame(fh.read())
    assert f.kind == "migration"
    assert f.header["uid"] == 42 and f.header["reason"] == "rebalance"
    assert f.header["trace"]["baggage"] == {"tenant": "gold"}
    # unknown-field tolerance on the committed bytes, not just fresh
    assert f.header["future_field_decoders_must_keep"] == {"x": [1, 2]}
    assert f.arrays["latents"].shape == (2, 11, 4)
    assert f.arrays["latents"].dtype == np.float32
    assert np.array_equal(f.arrays["tokens"],
                          np.arange(11, dtype=np.int32))
    assert f.meta["latents_q8"]["enc"] == "q8"
    magic, version, _ = struct.unpack_from("<4sHI", open(
        GOLDEN, "rb").read(), 0)
    assert magic == MAGIC and version == 1


# ------------------------------------------------------------------ #
# telemetry frame kind: the harvest channel's wire format
# ------------------------------------------------------------------ #
def test_golden_telemetry_bytes_are_stable():
    with open(GOLDEN_TELEMETRY, "rb") as fh:
        committed = fh.read()
    assert golden_telemetry_bytes() == committed, \
        "telemetry frame bytes drifted from the committed v1 " \
        "fixture — bump FRAME_VERSION instead of silently changing " \
        "the harvest wire format"


def test_golden_telemetry_decodes_with_pinned_content():
    with open(GOLDEN_TELEMETRY, "rb") as fh:
        f = decode_frame(fh.read())
    assert f.kind == "telemetry_ok"
    assert f.arrays == {}                 # header-only frame kind
    assert f.header["replica"] == 1 and f.header["v"] == 1
    assert f.header["now_us"] == 1234.5
    assert len(f.header["events"]) == 2
    assert f.header["events"][0]["name"] == "fabric.migration"
    assert f.header["events"][1]["args"]["uid"] == 42
    assert f.header["counters"]["q8_segments"] == 1
    assert f.header["thread_names"] == {"1": "fabric-worker"}
    # version tolerance on the committed bytes: unknown header fields
    # survive the decode (a v1 parent can harvest a richer worker)
    assert f.header["future_field_decoders_must_keep"] == {"x": [1]}


def test_telemetry_frame_rejects_unknown_version():
    buf = encode_frame("telemetry", {"t_send_us": 1.0},
                       version=FRAME_VERSION + 1)
    with pytest.raises(FrameVersionError):
        decode_frame(buf)


def test_telemetry_frame_seeded_fuzz_round_trip():
    """Seeded fuzz over harvest-reply shapes: arbitrary JSON-safe
    headers (random counters, event lists, nested metric rows) must
    round-trip exactly, and every truncation must raise a typed
    FrameError — a half-written harvest reply can never decode as a
    valid one."""
    rng = np.random.default_rng(20260807)
    for trial in range(20):
        n_events = int(rng.integers(0, 6))
        header = {
            "replica": int(rng.integers(0, 8)),
            "v": 1,
            "now_us": float(np.round(rng.uniform(0, 1e7), 3)),
            "events": [
                {"ph": "i", "name": f"fabric.ev{j}",
                 "ts": float(np.round(rng.uniform(0, 1e6), 3)),
                 "pid": 0, "tid": int(rng.integers(1, 4)),
                 "args": {"uid": int(rng.integers(0, 100))}}
                for j in range(n_events)],
            "dropped": int(rng.integers(0, 3)),
            "counters": {f"c{j}": int(rng.integers(0, 1 << 30))
                         for j in range(int(rng.integers(0, 5)))},
            "metrics": [{"name": "m", "labels": {"k": "v"},
                         "value": float(np.round(
                             rng.uniform(0, 1e9), 6))}],
            "rss_max_bytes": int(rng.integers(0, 1 << 33)),
        }
        buf = encode_frame("telemetry_ok", header)
        f = decode_frame(buf)
        assert f.kind == "telemetry_ok"
        got = {k: v for k, v in f.header.items()
               if k not in ("_segments", "kind")}
        assert got == header
        # truncation at a random interior point must raise, typed
        cut = int(rng.integers(1, len(buf)))
        with pytest.raises(FrameError):
            decode_frame(buf[:cut])
