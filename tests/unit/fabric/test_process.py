"""Process transport end-to-end: real spawned replica workers, real
socket crossings. Gates: bootstrap digest parity (the ``serialize()``
snapshot IS the process-side engine bootstrap), token-stream parity
with the in-memory transport on the same scenario, literal
kill-a-process recovery from the survivors' view, and measured-wire
accounting recorded beside (never instead of) the virtual-clock
pricing."""

import numpy as np
import pytest

from hcache_deepspeed_tpu.fabric import (ProcessTransport,
                                         canonical_digest)
from hcache_deepspeed_tpu.fabric.transport import migration_frame
from hcache_deepspeed_tpu.inference import RaggedInferenceEngineConfig
from hcache_deepspeed_tpu.serving import (FleetConfig, ReplicaState,
                                          RequestState, ServerConfig,
                                          ServingFleet,
                                          SimulatedEngine,
                                          VirtualClock)
from hcache_deepspeed_tpu.serving.fleet import Migration

pytestmark = pytest.mark.chaos


def sim_engine():
    return SimulatedEngine(RaggedInferenceEngineConfig(
        state_manager={"max_tracked_sequences": 8,
                       "max_ragged_batch_size": 256,
                       "max_ragged_sequence_count": 4,
                       "max_context": 128},
        kv_cache={"block_size": 8, "num_blocks": 16},
        hcache={"enable_latents": True}))


def make_fleet(transport, n=3):
    return ServingFleet(
        engines=[sim_engine() for _ in range(n)],
        clock=VirtualClock(),
        config=FleetConfig(
            server=ServerConfig(max_queue_depth=256,
                                kv_demand_fraction=float("inf")),
            transport=transport))


def drive(fleet, max_steps=5000):
    steps = 0
    while fleet.has_work:
        fleet.step()
        steps += 1
        assert steps < max_steps, fleet.snapshot()


def migrated_scenario(fleet):
    """Submit one request, force a mid-decode migration, drain."""
    req = fleet.submit(prompt=list(range(10)), max_new_tokens=12)
    fleet.step()
    fleet.step()
    assert req.state is RequestState.DECODE
    m = fleet.migrate(req.uid, dst=(req.replica + 1) % 3)
    assert m is not None
    drive(fleet)
    return req, m


def test_process_transport_end_to_end():
    """One spawn amortized over the whole contract: bootstrap parity,
    wire crossing with stream parity, snapshot audit, literal process
    kill with recovery, fallback on a dead wire, idempotent close."""
    # ground truth: the same scenario on the in-memory twin
    ref_req, _ = migrated_scenario(make_fleet(None))
    assert ref_req.state is RequestState.DONE
    ref_stream = list(ref_req.tokens_out)

    tr = ProcessTransport(spawn_timeout_s=120)
    fleet = make_fleet(tr)
    with tr:
        # -- bootstrap parity: every worker re-serialized to the very
        # digest the parent shipped
        assert tr.bootstrap_mismatches == 0
        for r in fleet.replicas:
            assert tr.workers[r.id].bootstrap_digest == \
                canonical_digest(r.engine.serialize())
        assert all(h.alive for h in tr.workers.values())

        # -- migration across a REAL process boundary: same stream
        req, m = migrated_scenario(fleet)
        assert req.state is RequestState.DONE
        assert list(req.tokens_out) == ref_stream
        assert m.mode == "restore"
        stats = tr.wire_stats()
        assert stats["deliveries"] >= 1
        assert stats["two_hop_deliveries"] >= 1
        assert stats["wire_bytes"] > 0
        assert stats["measured_wire_bytes_per_s"] > 0
        assert stats["local_fallbacks"] == 0

        # -- snapshot audit surface answers from the worker side
        live = next(r.id for r in fleet.replicas
                    if r.state is ReplicaState.UP)
        assert len(tr.snapshot_digest(live)) == 64

        # -- literal kill-a-process: survivors see the crash through
        # the liveness pass and the evacuated request still finishes
        req2 = fleet.submit(prompt=list(range(8)), max_new_tokens=8)
        fleet.step()
        fleet.step()
        victim = req2.replica
        tr.kill(victim)
        assert not tr.alive(victim)
        drive(fleet)
        assert fleet.replicas[victim].state is ReplicaState.DEAD
        assert req2.state is RequestState.DONE
        assert tr.wire_stats()["kills"] == 1
        assert fleet.counters["replica_crashes"] == 1

        # -- a dead wire downgrades to the in-memory path, never a
        # request failure: deliver to the killed worker falls back
        lat = np.ones((2, 3, 4), np.float32)

        class _Req:
            from hcache_deepspeed_tpu.inference.ragged.latents import \
                HostLatentStore
            latents = HostLatentStore(lat)

        fake = Migration(uid=999, src=-1, dst=victim,
                         nbytes=lat.nbytes, tokens=3, reason="crash",
                         depart_t=0.0, land_t=1.0, request=_Req())
        before = tr.local_fallbacks
        tr.deliver(fake, victim)
        assert tr.local_fallbacks == before + 1
        assert fake.request.latents is not None   # payload untouched

    tr.close()                                    # idempotent
    assert all(h.proc.poll() is not None for h in tr.workers.values())


def test_process_deliver_requires_start_and_frames_are_wire_ready():
    tr = ProcessTransport()
    m = Migration(uid=1, src=0, dst=1, nbytes=0, tokens=0,
                  reason="rebalance", depart_t=0.0, land_t=1.0)
    with pytest.raises(RuntimeError):
        tr.deliver(m, 1)
    # ship never needs the wire (departure may precede routing)
    assert tr.ship(m) == 0
    assert migration_frame(m).startswith(b"HDSF")
