"""Cross-process telemetry harvest: worker spans/counters crossing
the framed supervision channel, SIGKILL-resilient last-known caching
(a dead worker's telemetry survives on the parent-side handle, and a
harvest against it fails fast instead of hanging), and digest
invisibility — harvest on/off leaves the fleet's committed event
digest byte-identical to the in-memory twin's."""

import time

import pytest

from hcache_deepspeed_tpu.fabric import (ProcessTransport,
                                         canonical_digest)
from hcache_deepspeed_tpu.inference import RaggedInferenceEngineConfig
from hcache_deepspeed_tpu.serving import (FleetConfig, RequestState,
                                          ServerConfig, ServingFleet,
                                          SimulatedEngine,
                                          VirtualClock)
from hcache_deepspeed_tpu.telemetry import validate_prometheus_text

pytestmark = pytest.mark.chaos


def sim_engine():
    return SimulatedEngine(RaggedInferenceEngineConfig(
        state_manager={"max_tracked_sequences": 8,
                       "max_ragged_batch_size": 256,
                       "max_ragged_sequence_count": 4,
                       "max_context": 128},
        kv_cache={"block_size": 8, "num_blocks": 16},
        hcache={"enable_latents": True}))


def make_fleet(transport, n=3):
    return ServingFleet(
        engines=[sim_engine() for _ in range(n)],
        clock=VirtualClock(),
        config=FleetConfig(
            server=ServerConfig(max_queue_depth=256,
                                kv_demand_fraction=float("inf")),
            transport=transport))


def drive(fleet, max_steps=5000):
    steps = 0
    while fleet.has_work:
        fleet.step()
        steps += 1
        assert steps < max_steps, fleet.snapshot()


def migrated_scenario(fleet):
    req = fleet.submit(prompt=list(range(10)), max_new_tokens=12)
    fleet.step()
    fleet.step()
    assert req.state is RequestState.DECODE
    m = fleet.migrate(req.uid, dst=(req.replica + 1) % 3)
    assert m is not None
    drive(fleet)
    return req, m


def test_harvest_and_sigkill_resilience():
    """One spawn amortized over the harvest contract: live harvest
    carries spans + counters + a clock-offset estimate, the arrow
    pair (forward_out on the src worker, migrate_in on the dst) lands
    in real harvested streams, SIGKILL caches last-known telemetry on
    the parent handle without hanging the control loop, and the fleet
    surfaces stay wired up."""
    tr = ProcessTransport(spawn_timeout_s=120, harvest_every=2)
    fleet = make_fleet(tr)
    with tr:
        req, m = migrated_scenario(fleet)
        assert req.state is RequestState.DONE
        assert tr.harvest_all() == 3
        assert tr.harvests >= 3 and tr.harvest_failures == 0

        tel = tr.worker_telemetry
        assert sorted(tel) == [0, 1, 2]
        src, dst = int(m.src), int(m.dst)
        src_names = [e.get("name") for e in tel[src]["events"]]
        dst_names = [e.get("name") for e in tel[dst]["events"]]
        # the two-hop crossing is visible in REAL harvested streams:
        # the src worker marked the relay leaving, the dst worker
        # marked it landing (plus the span around the processing)
        assert "fabric.forward_out" in src_names
        assert "fabric.migrate_in" in dst_names
        assert "fabric.migration" in dst_names
        fwd = next(e for e in tel[src]["events"]
                   if e.get("name") == "fabric.forward_out")
        assert fwd["args"]["uid"] == req.uid
        # handshake-estimated clock offset: the workers spawned before
        # any harvest, so their perf_counter origins trail the
        # parent's — offset must be positive and finite
        for rid in (0, 1, 2):
            assert tel[rid]["clock_offset_us"] > 0
            assert tel[rid]["counters"]["frames"] >= 1
            assert tel[rid]["rss_max_bytes"] > 0

        stats = tr.telemetry_stats()
        assert stats["enabled"] and stats["harvests"] == tr.harvests
        assert stats["workers"]["0"]["alive"]

        # -- fleet surfaces: metrics_snapshot carries the measured
        # per-link block + the harvest accounting, and the Prometheus
        # exposition renders {replica, link}-labeled percentiles
        # validator-clean
        snap = fleet.metrics_snapshot()
        assert snap["worker_telemetry"]["harvests"] >= 3
        assert snap["measured_link"]["samples"] >= 1
        assert snap["measured_link"]["links"]
        text = fleet.prometheus_text()
        assert validate_prometheus_text(text) == []
        assert "wire_link_samples_total{" in text
        assert "wire_latency_seconds_p50{" in text
        assert 'link="' in text

        # -- SIGKILL: best-effort pre-kill harvest caches last-known
        # state; a later harvest fails FAST (no hang) and leaves the
        # cache intact
        victim = dst
        before = dict(tr.worker_telemetry[victim])
        before_events = len(before["events"])
        tr.kill(victim)
        t0 = time.perf_counter()
        assert tr.harvest(victim) is False
        assert time.perf_counter() - t0 < 5.0
        cached = tr.worker_telemetry[victim]
        assert len(cached["events"]) >= before_events
        assert cached["counters"]["frames"] >= 1
        # a dead worker never hangs harvest_all either, and failures
        # are tracked separately from the request-path fallbacks
        assert tr.harvest_all() == 2
        assert tr.wire_stats()["local_fallbacks"] == 0
    # close() is idempotent and ran its shutdown harvest
    assert tr.harvests > 3


def test_harvest_plane_is_digest_invisible():
    """The whole observability plane must not perturb the serving
    core: the same scenario with harvest aggressively on, harvest
    off, and on the in-memory twin produces byte-identical fleet
    event digests."""
    digests = {}
    for label, transport in (
            ("mem", None),
            ("harvest-on", ProcessTransport(spawn_timeout_s=120,
                                            harvest_every=1)),
            ("harvest-off", ProcessTransport(
                spawn_timeout_s=120, harvest_telemetry=False))):
        fleet = make_fleet(transport)
        if transport is None:
            req, _ = migrated_scenario(fleet)
        else:
            with transport:
                req, _ = migrated_scenario(fleet)
            assert (transport.harvests > 0) == \
                transport.harvest_telemetry
        assert req.state is RequestState.DONE
        digests[label] = canonical_digest(fleet.event_log())
    assert digests["harvest-on"] == digests["harvest-off"] == \
        digests["mem"], digests
