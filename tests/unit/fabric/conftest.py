"""Fabric suite harness: fleets built here get the same dynamic
lock-order sentinel the serving suite runs under."""

import pytest

from hcache_deepspeed_tpu.analysis.runtime import sentinel


@pytest.fixture(autouse=True)
def _lock_order_sentinel():
    with sentinel() as state:
        yield state
