"""In-memory transport: behavior-invisible default (digest twin),
``verify_frames`` codec soak on live fleet traffic, and the
migration<->frame payload round trip the process wire ships."""

import numpy as np
import pytest

from hcache_deepspeed_tpu.fabric import (InMemoryTransport,
                                         ReplicaTransport, WorkerDied,
                                         apply_frame, canonical_digest,
                                         decode_frame, migration_frame)
from hcache_deepspeed_tpu.inference import RaggedInferenceEngineConfig
from hcache_deepspeed_tpu.inference.ragged.latents import \
    HostLatentStore
from hcache_deepspeed_tpu.serving import (FleetConfig, RequestState,
                                          ServerConfig, ServingFleet,
                                          SimulatedEngine,
                                          VirtualClock)
from hcache_deepspeed_tpu.serving.fleet import Migration


def sim_engine(num_blocks=16):
    return SimulatedEngine(RaggedInferenceEngineConfig(
        state_manager={"max_tracked_sequences": 8,
                       "max_ragged_batch_size": 256,
                       "max_ragged_sequence_count": 4,
                       "max_context": 128},
        kv_cache={"block_size": 8, "num_blocks": num_blocks},
        hcache={"enable_latents": True}))


def make_fleet(n=3, transport=None):
    return ServingFleet(
        engines=[sim_engine() for _ in range(n)],
        clock=VirtualClock(),
        config=FleetConfig(
            server=ServerConfig(max_queue_depth=256,
                                kv_demand_fraction=float("inf")),
            transport=transport))


def drive(fleet, max_steps=5000):
    steps = 0
    while fleet.has_work:
        fleet.step()
        steps += 1
        assert steps < max_steps, fleet.snapshot()


def run_migrating_trace(transport):
    """A seeded scenario with forced cross-replica migrations; returns
    (fleet, requests, event-log digest)."""
    fleet = make_fleet(transport=transport)
    reqs = [fleet.submit(prompt=list(range(4 + i)), max_new_tokens=8)
            for i in range(4)]
    fleet.step()
    fleet.step()
    for i, r in enumerate(reqs):
        if r.state is RequestState.DECODE:
            fleet.migrate(r.uid, dst=(r.replica + 1) % 3)
    drive(fleet)
    return fleet, reqs, canonical_digest(fleet.event_log())


# ------------------------------------------------------------------ #
# default wiring + interface
# ------------------------------------------------------------------ #
def test_fleet_defaults_to_in_memory_transport():
    fleet = make_fleet()
    assert isinstance(fleet.transport, InMemoryTransport)
    assert fleet.transport.fleet is fleet
    assert fleet.summary()["transport"] == "in-memory"


def test_abstract_transport_surface():
    t = ReplicaTransport()
    assert t.alive(0) is True
    assert t.wire_stats() == {}
    with pytest.raises(NotImplementedError):
        t.ship(None)
    with pytest.raises(NotImplementedError):
        t.kill(0)
    with t:                      # start/close are no-op context mgr
        pass


def test_worker_died_is_shaped_like_an_injected_fault():
    exc = WorkerDied(2, "kill -9")
    assert exc.replica == 2 and exc.hit == 0
    assert "worker died" in str(exc)


def test_in_memory_ship_tickets_are_sequential():
    t = InMemoryTransport()
    m = Migration(uid=1, src=0, dst=-1, nbytes=64, tokens=3,
                  reason="crash", depart_t=0.0, land_t=1.0)
    assert [t.ship(m) for _ in range(3)] == [0, 1, 2]
    assert t.shipped == 3 and t.bytes_registered == 3 * 64
    t.deliver(m, 1)
    assert t.delivered == 1
    stats = t.wire_stats()
    assert stats["transport"] == "in-memory"
    assert stats["frames_verified"] == 0


# ------------------------------------------------------------------ #
# migration <-> frame payload round trip
# ------------------------------------------------------------------ #
def test_migration_frame_round_trip_restores_store_and_trace():
    rng = np.random.default_rng(0)
    lat = rng.standard_normal((2, 9, 4)).astype(np.float32)

    class _Req:
        latents = HostLatentStore(lat)

    m = Migration(uid=7, src=0, dst=2, nbytes=lat.nbytes, tokens=9,
                  reason="rebalance", depart_t=0.0, land_t=1.0,
                  request=_Req(),
                  trace_wire={"v": 1, "trace_id": "aa", "uid": 7,
                              "hops": 0})
    frame = decode_frame(migration_frame(m))
    assert frame.kind == "migration"
    assert frame.header["uid"] == 7
    # scribble, then land the frame back: bytes + types restored
    m.request.latents = None
    m.trace_wire = None
    apply_frame(m, frame)
    assert isinstance(m.request.latents, HostLatentStore)
    assert m.request.latents.shape == (2, 9, 4)
    assert np.array_equal(np.asarray(m.request.latents), lat)
    assert m.trace_wire["trace_id"] == "aa"


def test_migration_frame_prefix_broadcast_payload():
    payload = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    m = Migration(uid=9, src=1, dst=0, nbytes=payload.nbytes,
                  tokens=3, reason="prefix_broadcast", depart_t=0.0,
                  land_t=1.0, prefix_tokens=(5, 6, 7),
                  payload=payload)
    frame = decode_frame(migration_frame(m))
    assert frame.header["prefix_tokens"] == [5, 6, 7]
    m.payload = None
    apply_frame(m, frame)
    assert np.array_equal(m.payload, payload)


# ------------------------------------------------------------------ #
# digest twin + verify_frames soak
# ------------------------------------------------------------------ #
def test_verify_frames_soak_is_digest_invisible():
    """The codec soak (every delivery round-tripped through the binary
    frame) must neither corrupt payloads nor perturb the event log:
    same seed, same digest, frames actually verified."""
    _, base_reqs, base_digest = run_migrating_trace(None)
    soak = InMemoryTransport(verify_frames=True)
    _, soak_reqs, soak_digest = run_migrating_trace(soak)
    assert soak_digest == base_digest
    assert soak.frames_verified > 0
    assert soak.delivered >= soak.frames_verified
    for a, b in zip(base_reqs, soak_reqs):
        assert a.state == b.state
        assert list(a.tokens_out) == list(b.tokens_out)


def test_verify_frames_trips_on_corrupted_payload():
    t = InMemoryTransport(verify_frames=True)
    lat = np.ones((2, 4, 4), np.float32)

    class _Req:
        latents = HostLatentStore(lat)

    class _Lying(HostLatentStore):
        # dtype disagreement between what ships and what landed
        def __array__(self, dtype=None, copy=None):
            return super().__array__(np.float16)

    m = Migration(uid=1, src=0, dst=1, nbytes=lat.nbytes, tokens=4,
                  reason="rebalance", depart_t=0.0, land_t=1.0,
                  request=_Req())
    m.request.latents = _Lying(lat)
    with pytest.raises(AssertionError):
        t.deliver(m, 1)
