"""Scale events on the process transport: a SIGSTOP'd worker hits the
typed control-socket deadline instead of wedging the parent, a
mid-scale-up spawn kill burns one supervised attempt and recovers,
retirement reaps the worker only after the drain landed, and retry
exhaustion turns into a clean ``ScaleUpAborted`` with the prior fleet
shape intact (ISSUE 19)."""

import os
import signal
import time

import pytest

from hcache_deepspeed_tpu.fabric import (FabricTimeout,
                                         ProcessTransport,
                                         canonical_digest)
from hcache_deepspeed_tpu.inference import RaggedInferenceEngineConfig
from hcache_deepspeed_tpu.resilience import (FaultPlan, FaultRule,
                                             injected)
from hcache_deepspeed_tpu.serving import (FleetConfig, ReplicaState,
                                          RequestState, ScaleUpAborted,
                                          ServerConfig, ServingFleet,
                                          SimulatedEngine,
                                          VirtualClock)

pytestmark = pytest.mark.chaos


def sim_engine():
    return SimulatedEngine(RaggedInferenceEngineConfig(
        state_manager={"max_tracked_sequences": 8,
                       "max_ragged_batch_size": 256,
                       "max_ragged_sequence_count": 4,
                       "max_context": 128},
        kv_cache={"block_size": 8, "num_blocks": 16},
        hcache={"enable_latents": True}))


def make_fleet(transport, n):
    return ServingFleet(
        engine_factory=sim_engine,
        clock=VirtualClock(),
        config=FleetConfig(
            n_replicas=n,
            server=ServerConfig(max_queue_depth=256,
                                kv_demand_fraction=float("inf")),
            transport=transport))


def drive(fleet, max_steps=5000):
    steps = 0
    while fleet.has_work:
        fleet.step()
        steps += 1
        assert steps < max_steps, fleet.snapshot()


def test_sigstop_worker_hits_typed_deadline_not_a_wedge():
    """Satellite 1: every blocking control-socket read sits behind a
    typed deadline — a SIGSTOP'd worker raises ``FabricTimeout``
    (an ``OSError``, carrying replica + op) and bumps the
    ``io_timeouts`` counter instead of hanging the parent forever."""
    tr = ProcessTransport(spawn_timeout_s=120, io_timeout_s=1.0)
    fleet = make_fleet(tr, n=1)
    with tr:
        assert fleet.replicas[0].state is ReplicaState.UP
        h = tr.workers[0]
        os.kill(h.proc.pid, signal.SIGSTOP)
        try:
            t0 = time.monotonic()
            with pytest.raises(FabricTimeout) as ei:
                tr.snapshot_digest(0)
            elapsed = time.monotonic() - t0
        finally:
            os.kill(h.proc.pid, signal.SIGCONT)
        assert isinstance(ei.value, OSError)
        assert ei.value.replica == 0
        assert ei.value.op == "snapshot"
        # bounded by the io deadline, nowhere near a wedge
        assert elapsed < 30.0
        assert tr.io_timeouts == 1
        assert tr.wire_stats()["io_timeouts"] == 1


def test_scale_lifecycle_under_process_transport():
    """One fleet amortized over the whole scale contract: a scale-up
    whose first spawn is chaos-killed recovers under the supervisor's
    bounded retry, the new worker passes strict bootstrap parity and
    serves real requests, retirement drains then reaps the process,
    and a retry-exhausted revival aborts cleanly."""
    tr = ProcessTransport(spawn_timeout_s=120, io_timeout_s=60,
                          spawn_retries=2, spawn_backoff_s=0.05)
    fleet = make_fleet(tr, n=2)
    with tr:
        # -- scale-up with the first spawn killed mid-bring-up
        plan = FaultPlan(seed=0, rules=[
            FaultRule("scale.spawn", at_hits=(1,), max_faults=1)])
        with injected(plan) as inj:
            rid = fleet.add_replica()
        assert inj.fired.get("scale.spawn", 0) == 1
        assert rid == 2
        assert tr.scale_spawns == 1
        assert tr.scale_spawn_failures == 1
        assert fleet.counters["scale_ups"] == 1

        # -- the retried worker is really up, with bootstrap parity
        h = tr.workers[rid]
        assert h.alive
        assert h.bootstrap_digest == \
            canonical_digest(fleet.replicas[rid].engine.serialize())

        # -- and it serves: traffic lands on 3 live replicas
        reqs = [fleet.submit(prompt=list(range(6 + i)),
                             max_new_tokens=6) for i in range(9)]
        drive(fleet)
        assert all(r.state is RequestState.DONE for r in reqs)

        # -- retire: drain lands first, then the process is reaped
        fleet.retire_replica(rid)
        for _ in range(50):
            if fleet.replicas[rid].state is ReplicaState.STOPPED:
                break
            fleet.step()
        assert fleet.replicas[rid].state is ReplicaState.STOPPED
        assert fleet.counters["retires_completed"] == 1
        assert tr.scale_retired == 1
        assert not tr.workers[rid].alive
        assert tr.workers[rid].proc.poll() is not None

        # -- revival with every spawn attempt killed: clean abort,
        # prior shape, replica stays STOPPED
        plan = FaultPlan(seed=0, rules=[
            FaultRule("scale.spawn", at_hits=(1, 2), max_faults=2)])
        with injected(plan):
            with pytest.raises(ScaleUpAborted):
                fleet.add_replica()
        assert fleet.replicas[rid].state is ReplicaState.STOPPED
        assert len(fleet.replicas) == 3
        assert fleet.counters["scale_up_aborts"] == 1
        assert tr.scale_spawn_failures == 1 + 2
        assert tr.wire_stats()["workers_alive"] == 2

        # zero requests touched by any of it
        assert all(r.state is RequestState.DONE for r in reqs)
        assert fleet.migration_balance_ok and not fleet.in_transit
