import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from hcache_deepspeed_tpu import comm
from hcache_deepspeed_tpu.parallel.topology import (TopologySpec,
                                                    initialize_topology)


def _shmap(f, topo, in_specs, out_specs):
    return jax.shard_map(f, mesh=topo.mesh, in_specs=in_specs,
                         out_specs=out_specs)


class TestCollectives:
    def test_all_reduce_sum(self, eight_devices):
        topo = initialize_topology(TopologySpec(data=8))
        x = jnp.arange(8.0)
        f = _shmap(lambda v: comm.all_reduce(v, group="data"), topo,
                   P("data"), P("data"))
        out = f(x)
        np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))

    def test_all_reduce_avg_max(self, eight_devices):
        topo = initialize_topology(TopologySpec(data=8))
        x = jnp.arange(8.0)
        favg = _shmap(lambda v: comm.all_reduce(v, "avg", "data"), topo,
                      P("data"), P("data"))
        fmax = _shmap(lambda v: comm.all_reduce(v, "max", "data"), topo,
                      P("data"), P("data"))
        np.testing.assert_allclose(np.asarray(favg(x)), np.full(8, 3.5))
        np.testing.assert_allclose(np.asarray(fmax(x)), np.full(8, 7.0))

    def test_all_gather_tiled(self, eight_devices):
        topo = initialize_topology(TopologySpec(data=8))
        x = jnp.arange(16.0).reshape(8, 2)
        f = _shmap(lambda v: comm.all_gather(v, group="data"), topo,
                   P("data"), P("data", None))
        out = f(x)  # each shard gathers all 8 rows -> [8*8? no: tiled 8,2]*8
        assert out.shape == (64, 2)

    def test_reduce_scatter(self, eight_devices):
        topo = initialize_topology(TopologySpec(data=8))
        x = jnp.ones((8, 4))  # every device sees the full array
        f = _shmap(lambda v: comm.reduce_scatter(v, group="data"), topo,
                   P(None, None), P("data", None))
        out = f(x)  # each device keeps 1 row of the sum
        assert out.shape == (8, 4)
        np.testing.assert_allclose(np.asarray(out), np.full((8, 4), 8.0))

    def test_all_to_all(self, eight_devices):
        topo = initialize_topology(TopologySpec(data=8))
        x = jnp.arange(64.0).reshape(8, 8)
        f = _shmap(lambda v: comm.all_to_all(v, group="data", split_axis=1,
                                             concat_axis=0), topo,
                   P("data", None), P("data", None))
        out = f(x)
        assert out.shape == (64, 1)

    def test_broadcast(self, eight_devices):
        topo = initialize_topology(TopologySpec(data=8))
        x = jnp.arange(8.0)
        f = _shmap(lambda v: comm.broadcast(v, src=3, group="data"), topo,
                   P("data"), P("data"))
        np.testing.assert_allclose(np.asarray(f(x)), np.full(8, 3.0))

    def test_ppermute_ring(self, eight_devices):
        topo = initialize_topology(TopologySpec(data=8))
        x = jnp.arange(8.0)
        perm = [(i, (i + 1) % 8) for i in range(8)]
        f = _shmap(lambda v: comm.ppermute(v, perm, group="data"), topo,
                   P("data"), P("data"))
        np.testing.assert_allclose(np.asarray(f(x)),
                                   np.roll(np.arange(8.0), 1))


class TestCommsLogger:
    def test_logging_records_ops(self, eight_devices):
        comm.configure(enabled=True)
        logger = comm.get_comms_logger()
        logger.reset()
        topo = initialize_topology(TopologySpec(data=8))
        x = jnp.arange(8.0)
        f = _shmap(lambda v: comm.all_reduce(v, group="data"), topo,
                   P("data"), P("data"))
        f(x)
        assert any("all_reduce" in k for k in logger.comms_dict)
        comm.configure(enabled=False)

    def test_config_block_wires_the_logger(self, eight_devices):
        """The reference's ``comms_logger`` config block configures the
        global logger through initialize (comms_config.py)."""
        import hcache_deepspeed_tpu as hds
        import numpy as np
        from hcache_deepspeed_tpu.models.gpt2 import (GPT2LMHeadModel,
                                                      gpt2_tiny)
        logger = comm.get_comms_logger()
        logger.reset()
        batch = {"input_ids": np.zeros((8, 16), np.int32)}
        try:
            hds.initialize(
                model=GPT2LMHeadModel(gpt2_tiny()), example_batch=batch,
                config={"train_batch_size": 8,
                        "optimizer": {"type": "AdamW",
                                      "params": {"lr": 1e-3}},
                        "comms_logger": {"enabled": True,
                                         "prof_ops": ["all_gather"],
                                         "prof_all": False}})
            assert logger.enabled
            assert logger.prof_ops == ["all_gather"]
            assert logger.prof_all is False
        finally:
            comm.configure(enabled=False, prof_all=True, prof_ops=[])

    def test_axis_summary_and_monitor_events(self, eight_devices):
        """Per-axis volume breakdown — the partitioned-parameter
        profiler analog (reference:
        runtime/zero/partitioned_param_profiler.py count/numel per
        event, surfaced to the monitor)."""
        comm.configure(enabled=True)
        logger = comm.get_comms_logger()
        logger.reset()
        topo = initialize_topology(TopologySpec(data=4, tensor=2))
        x = jnp.arange(8.0)
        f = jax.shard_map(
            lambda v: comm.all_gather(
                comm.all_reduce(v, group="tensor"), group="data"),
            mesh=topo.mesh, in_specs=P(("data", "tensor")),
            out_specs=P(None), check_vma=False)
        f(x)
        summary = logger.axis_summary()
        assert "all_reduce" in summary and "all_gather" in summary
        assert "tensor" in summary["all_reduce"]
        assert "data" in summary["all_gather"]
        count, total = summary["all_gather"]["data"]
        assert count >= 1 and total > 0
        events = logger.monitor_events(step=7)
        assert any(tag == "Comms/all_gather@data" and step == 7
                   for tag, _, step in events)
        comm.configure(enabled=False)
