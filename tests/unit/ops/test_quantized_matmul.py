"""Fused int8-weight matmul (reference: the weight-only quantized linear
path, deepspeed/inference/quantization + csrc/quantization)."""

import jax.numpy as jnp
import numpy as np

from hcache_deepspeed_tpu.ops.quantized_matmul import (
    pallas_quantized_matmul, quantize_for_matmul,
    reference_quantized_matmul)


def _mk(M=64, K=128, N=256, group_k=32, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((M, K)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((K, N)), jnp.float32)
    q, scale = quantize_for_matmul(w, group_k=group_k)
    return x, w, q, scale


def test_quantize_for_matmul_roundtrip():
    _, w, q, scale = _mk()
    K, N = q.shape
    back = (q.astype(jnp.float32).reshape(K // 32, 32, N)
            * np.asarray(scale)[:, None, :]).reshape(K, N)
    err = np.abs(np.asarray(back) - np.asarray(w)).max()
    assert err < np.abs(np.asarray(w)).max() / 100


def test_reference_matches_dense_matmul():
    x, w, q, scale = _mk()
    out = reference_quantized_matmul(x, q, scale, group_k=32)
    dense = np.asarray(x) @ np.asarray(w)
    rel = np.abs(np.asarray(out) - dense).max() / np.abs(dense).max()
    assert rel < 0.02


def test_pallas_interpret_matches_reference():
    x, w, q, scale = _mk()
    ref = reference_quantized_matmul(x, q, scale, group_k=32)
    out = pallas_quantized_matmul(x, q, scale, group_k=32, block_m=32,
                                  block_n=128, block_k=64, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-3, rtol=1e-3)


def test_shape_fallback():
    """block_k follows group_k (one scale row per k-block), so odd K
    that still divides by the group runs the kernel — many k-blocks,
    looser fp32 accumulation-order tolerance — and K NOT divisible by
    the group takes the reference path."""
    x, w, q, scale = _mk(M=32, K=320, N=256, group_k=32, seed=1)
    out = pallas_quantized_matmul(x, q, scale, group_k=32, block_m=32,
                                  block_n=256, interpret=True)
    ref = reference_quantized_matmul(x, q, scale, group_k=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4)
    # a ragged M that does not divide block_m trips the fallback (the
    # reference path), which must agree exactly
    x2, w2, q2, scale2 = _mk(M=32, K=192, N=256, group_k=64, seed=2)
    out2 = pallas_quantized_matmul(x2[:17], q2, scale2, group_k=64,
                                   block_m=16, interpret=True)
    ref2 = reference_quantized_matmul(x2[:17], q2, scale2, group_k=64)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2),
                               atol=1e-5)


def test_tile_chooser_covers_7b_shapes():
    """The chosen (block_n, groups_per_block) must tile every Llama-7B
    matmul at both serving group sizes — a non-dividing tile silently
    drops the shape onto the dequant fallback (observed on chip: qkv
    and gate_up — 74% of the weight bytes — ran dequantized) — and must
    keep the grid small: per-step Mosaic dispatch overhead is the cost
    driver in both regimes (measured 478 GB/s at 32 one-group decode
    steps vs 681 GB/s dense; 7B prefill 15x off the streaming ceiling
    at 1536 steps/matmul)."""
    from hcache_deepspeed_tpu.ops.quantized_matmul import _choose_tiles
    h, ffn = 4096, 11008
    shapes = {"qkv": (h, 3 * h), "o": (h, h),
              "gate_up": (h, 2 * ffn), "down": (ffn, h)}
    for M, bm, step_cap in ((8, 8, 50), (64, 64, 200)):
        for gk in (128, 256):
            for name, (K, N) in shapes.items():
                if K % gk:
                    continue
                got = _choose_tiles(M, K, N, gk, bm)
                assert got is not None, (name, gk, M)
                bn, gpb = got
                assert N % bn == 0 and bn % 128 == 0, (name, gk, bn)
                assert (K // gk) % gpb == 0, (name, gk, gpb)
                steps = (M // bm) * (N // bn) * (K // (gpb * gk))
                assert steps <= step_cap, (name, gk, M, steps)


def test_sliced_scale_path_numeric():
    """Numerics of the gpb%8==0 STATIC scale-row path (the blocking
    every 7B qkv/o matmul takes at serving group sizes) and of the
    default chooser-driven blocking — the tile-arithmetic test above
    cannot catch a wrong sliced BlockSpec index map."""
    import jax

    from hcache_deepspeed_tpu.ops.quantized_matmul import _choose_tiles
    # K=1024, group 128 -> G=8 -> chooser picks gpb=8 (sliced scale)
    x, w, q, scale = _mk(M=8, K=1024, N=256, group_k=128, seed=3)
    bn, gpb = _choose_tiles(8, 1024, 256, 128, 8)
    assert gpb % 8 == 0, "shape no longer drives the sliced-scale path"
    ref = reference_quantized_matmul(x, q, scale, group_k=128)
    out = pallas_quantized_matmul(x, q, scale, group_k=128,
                                  interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-3, rtol=1e-3)
    # compute regime (M>32) through the default chooser
    x2, _, q2, scale2 = _mk(M=64, K=1024, N=256, group_k=128, seed=4)
    ref2 = reference_quantized_matmul(x2, q2, scale2, group_k=128)
    out2 = pallas_quantized_matmul(x2, q2, scale2, group_k=128,
                                   interpret=True)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2),
                               atol=1e-3, rtol=1e-3)


def test_make_batched_matches_one_shot():
    """Per-layer streaming quantization (the 7B OOM fix) must produce
    exactly the one-shot stacked result — including from a host numpy
    leaf, which streams one layer at a time."""
    import numpy as onp

    from hcache_deepspeed_tpu.ops.quantized_matmul import \
        MatmulQuantizedTensor
    rng = onp.random.default_rng(0)
    w = rng.standard_normal((3, 64, 48)).astype(onp.float32)
    one = MatmulQuantizedTensor.make(jnp.asarray(w), group_k=32)
    for leaf in (jnp.asarray(w), w):          # device and host inputs
        bat = MatmulQuantizedTensor.make_batched(leaf, group_k=32)
        onp.testing.assert_array_equal(onp.asarray(bat.q),
                                       onp.asarray(one.q))
        onp.testing.assert_allclose(onp.asarray(bat.scale),
                                    onp.asarray(one.scale), rtol=1e-6)
        assert bat.group_k == one.group_k


def test_choose_tiles_scale_with_activation_bytes():
    """The VMEM estimate must price the x/out tiles at the ACTUAL
    activation itemsize: a 4-byte (fp32) input picks a smaller tile
    than the 2-byte (bf16) default — the bf16 blocking would overflow
    the budget once the tiles are really fp32."""
    from hcache_deepspeed_tpu.ops.quantized_matmul import _choose_tiles
    M, K, N, G, BM = 256, 4096, 4096, 256, 256
    bn2, gpb2 = _choose_tiles(M, K, N, G, BM, x_bytes=2)
    bn4, gpb4 = _choose_tiles(M, K, N, G, BM, x_bytes=4)
    assert (bn4, gpb4) != (bn2, gpb2)

    def vmem(bn, gpb, xb):
        bk = gpb * G
        rows = gpb if gpb % 8 == 0 else K // G
        return (2 * bk * bn + 2 * BM * bk * xb + 2 * rows * bn * 4
                + BM * bn * 4 + 2 * BM * bn * xb)

    budget = 10 * 2**20
    assert vmem(bn4, gpb4, 4) <= budget
    # the bf16 choice priced at fp32 bytes overflows — exactly the
    # miscount the dtype-derived estimate fixes
    assert vmem(bn2, gpb2, 4) > budget


def test_reference_fallback_recorded_and_warned_once():
    """The silent reference-path fallback must leave a trail: counters
    by reason + the last shape in fallback_debug_info(), and ONE
    warning for the first fallback (a perf run can then check it
    measured the kernel, not the dequant path). The repo logger does
    not propagate, so warn-once is asserted via the debug record's
    ``warned`` latch rather than captured records."""
    from hcache_deepspeed_tpu.ops import quantized_matmul as qmm
    x, w, q, scale = _mk(M=32, K=192, N=256, group_k=64, seed=3)
    saved = dict(qmm._FALLBACK_DEBUG)
    saved["by_reason"] = dict(saved["by_reason"])
    try:
        qmm._FALLBACK_DEBUG.update(count=0, by_reason={}, last=None,
                                   warned=False)
        # ragged M against an explicit block_m: 17 % 8 != 0
        out = qmm.pallas_quantized_matmul(
            x[:17], q, scale, group_k=64, block_m=8, interpret=True)
        assert qmm._FALLBACK_DEBUG["warned"]      # first fallback warns
        out2 = qmm.pallas_quantized_matmul(
            x[:17], q, scale, group_k=64, block_m=8, interpret=True)
        ref = qmm.reference_quantized_matmul(x[:17], q, scale,
                                             group_k=64)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-4)
        np.testing.assert_allclose(np.asarray(out2), np.asarray(ref),
                                   atol=1e-4)
        info = qmm.fallback_debug_info()
        assert info["count"] == 2
        assert info["by_reason"] == {"tile_misaligned": 2}
        reason, M, K, N, block = info["last"]
        assert (reason, M, K, N) == ("tile_misaligned", 17, 192, 256)
    finally:
        qmm._FALLBACK_DEBUG.update(saved)


class TestFusedConsumption:
    """The ZeRO++ fused qwZ consumption contract: a
    ``MatmulQuantizedTensor`` handed to an ``nn.Dense`` through the
    interceptor computes through the fused kernel and is equal to
    dequant-then-matmul within the kernel's documented tile tolerance
    (atol/rtol 1e-3 at fp32, the pallas-vs-reference bound above)."""

    def test_interceptor_matches_dequant_then_matmul(self):
        import flax.linen as nn
        import jax

        from hcache_deepspeed_tpu.ops.quantized_matmul import (
            MatmulQuantizedTensor, fused_dense_interceptor)
        rng = np.random.default_rng(0)
        w = jnp.asarray(rng.standard_normal((128, 256)), jnp.float32)
        b = jnp.asarray(rng.standard_normal((256,)), jnp.float32)
        x = jnp.asarray(rng.standard_normal((4, 9, 128)), jnp.float32)
        mqt = MatmulQuantizedTensor.make(w, group_k=32)
        dense = nn.Dense(256)
        with nn.intercept_methods(fused_dense_interceptor()):
            y = dense.apply({"params": {"kernel": mqt, "bias": b}}, x)
        ref = x @ mqt.dequantize() + b
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   atol=1e-3, rtol=1e-3)
        # a plain fp kernel passes through the interceptor untouched
        with nn.intercept_methods(fused_dense_interceptor()):
            y2 = dense.apply({"params": {"kernel": w, "bias": b}}, x)
        np.testing.assert_allclose(np.asarray(y2),
                                   np.asarray(x @ w + b), atol=1e-4,
                                   rtol=1e-4)

    def test_dequantize_oracle(self):
        from hcache_deepspeed_tpu.ops.quantized_matmul import (
            MatmulQuantizedTensor, reference_quantized_matmul)
        rng = np.random.default_rng(1)
        w = jnp.asarray(rng.standard_normal((64, 128)), jnp.float32)
        x = jnp.asarray(rng.standard_normal((8, 64)), jnp.float32)
        mqt = MatmulQuantizedTensor.make(w, group_k=32)
        ref = reference_quantized_matmul(x, mqt.q, mqt.scale, group_k=32)
        np.testing.assert_allclose(np.asarray(x @ mqt.dequantize()),
                                   np.asarray(ref), atol=1e-4, rtol=1e-4)

    def test_gathered_shard_assembly_matches_whole_weight(self):
        """Per-shard quantize_for_matmul + concat along the contraction
        dim (what the bucketed gather ships) == one valid fused-layout
        weight: group boundaries tile each shard evenly, so the
        assembled (q, scale) dequantizes to the per-shard dequants."""
        from hcache_deepspeed_tpu.ops.quantized_matmul import (
            MatmulQuantizedTensor, quantize_for_matmul)
        rng = np.random.default_rng(2)
        w = jnp.asarray(rng.standard_normal((128, 64)), jnp.float32)
        shards = jnp.split(w, 4, axis=0)           # [32, 64] each
        qs, ss = zip(*[quantize_for_matmul(s, group_k=32)
                       for s in shards])
        assembled = MatmulQuantizedTensor(
            jnp.concatenate(qs, axis=0), jnp.concatenate(ss, axis=0), 32)
        per_shard = jnp.concatenate(
            [MatmulQuantizedTensor(q, s, 32).dequantize()
             for q, s in zip(qs, ss)], axis=0)
        np.testing.assert_array_equal(np.asarray(assembled.dequantize()),
                                      np.asarray(per_shard))
