"""Evoformer attention: Pallas kernel vs jnp reference numerics.

Reference analog: the DS4Science evoformer attention tests
(``tests/unit/ops/deepspeed4science/test_DS4Sci_EvoformerAttention.py``) —
kernel-vs-eager numerics for fwd and every gradient, over the two bias
kinds. Runs in interpret mode on the CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hcache_deepspeed_tpu.ops.evoformer_attention import (
    evoformer_attention, pallas_evoformer_attention,
    reference_evoformer_attention)

B, N, S, H, D = 1, 3, 128, 2, 16


def _inputs(seed=0, with_b1=True, with_b2=True, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda *shape: jnp.asarray(
        rng.standard_normal(shape).astype(np.float32), dtype=dtype)
    q, k, v = mk(B, N, S, H, D), mk(B, N, S, H, D), mk(B, N, S, H, D)
    bias1 = mk(B, N, 1, 1, S) if with_b1 else None
    bias2 = mk(B, 1, H, S, S) if with_b2 else None
    return q, k, v, bias1, bias2


class TestEvoformerAttention:

    @pytest.mark.parametrize("with_b1,with_b2", [(False, False),
                                                 (True, False),
                                                 (False, True),
                                                 (True, True)])
    def test_fwd_matches_reference(self, with_b1, with_b2):
        q, k, v, b1, b2 = _inputs(0, with_b1, with_b2)
        want = reference_evoformer_attention(q, k, v, b1, b2)
        got = pallas_evoformer_attention(q, k, v, b1, b2, interpret=True)
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)

    def test_bwd_matches_reference(self):
        q, k, v, b1, b2 = _inputs(1)

        def loss(fn):
            return lambda q, k, v, b1, b2: jnp.sum(
                fn(q, k, v, b1, b2) ** 2)

        want = jax.grad(loss(reference_evoformer_attention),
                        argnums=(0, 1, 2, 3, 4))(q, k, v, b1, b2)
        got = jax.grad(
            loss(lambda *a: pallas_evoformer_attention(*a, interpret=True)),
            argnums=(0, 1, 2, 3, 4))(q, k, v, b1, b2)
        for g, w, name in zip(got, want, "q k v bias1 bias2".split()):
            assert g.shape == w.shape, name
            np.testing.assert_allclose(g, w, atol=5e-4, rtol=5e-4,
                                       err_msg=name)

    def test_bwd_single_bias(self):
        q, k, v, b1, _ = _inputs(2, with_b2=False)
        fn_ref = lambda q, b: jnp.sum(
            reference_evoformer_attention(q, k, v, b, None) ** 2)
        fn_pal = lambda q, b: jnp.sum(
            pallas_evoformer_attention(q, k, v, b, None,
                                       interpret=True) ** 2)
        want = jax.grad(fn_ref, argnums=(0, 1))(q, b1)
        got = jax.grad(fn_pal, argnums=(0, 1))(q, b1)
        np.testing.assert_allclose(got[0], want[0], atol=5e-4, rtol=5e-4)
        np.testing.assert_allclose(got[1], want[1], atol=5e-4, rtol=5e-4)

    def test_dispatch_recognises_bias_shapes(self):
        q, k, v, b1, b2 = _inputs(3)
        want = reference_evoformer_attention(q, k, v, b1, b2)
        got = evoformer_attention(q, k, v, biases=[b2, b1])
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)

    def test_mask_bias_masks(self):
        # a -inf-style bias1 on the tail keys zeroes their attention
        q, k, v, b1, _ = _inputs(4, with_b2=False)
        b1 = b1.at[..., S // 2:].set(-1e9)
        out = pallas_evoformer_attention(q, k, v, b1, None, interpret=True)
        v2 = v.at[:, :, S // 2:].set(123.0)  # masked keys can't leak
        out2 = pallas_evoformer_attention(q, k, v2, b1, None,
                                          interpret=True)
        np.testing.assert_allclose(out, out2, atol=1e-5)

    def test_multi_block_fwd_bwd(self):
        # S=256 with block 128 → nq=nk=2: exercises the online-softmax
        # cross-block rescaling, the ki/qi accumulator epilogues, and the
        # db1 fused (h, qi) accumulation axis
        rng = np.random.default_rng(6)
        mk = lambda *shape: jnp.asarray(
            rng.standard_normal(shape).astype(np.float32))
        S2 = 256
        q, k, v = (mk(1, 2, S2, 2, 16) for _ in range(3))
        b1, b2 = mk(1, 2, 1, 1, S2), mk(1, 1, 2, S2, S2)
        want = reference_evoformer_attention(q, k, v, b1, b2)
        got = pallas_evoformer_attention(q, k, v, b1, b2, interpret=True)
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)

        def loss(fn):
            return lambda *a: jnp.sum(fn(*a) ** 2)

        want_g = jax.grad(loss(reference_evoformer_attention),
                          argnums=(0, 1, 2, 3, 4))(q, k, v, b1, b2)
        got_g = jax.grad(
            loss(lambda *a: pallas_evoformer_attention(*a, interpret=True)),
            argnums=(0, 1, 2, 3, 4))(q, k, v, b1, b2)
        for g, w, name in zip(got_g, want_g, "q k v bias1 bias2".split()):
            np.testing.assert_allclose(g, w, atol=5e-4, rtol=5e-4,
                                       err_msg=name)

    def test_odd_seq_falls_back(self):
        rng = np.random.default_rng(5)
        mk = lambda *shape: jnp.asarray(
            rng.standard_normal(shape).astype(np.float32))
        q = mk(1, 2, 100, 2, 16)
        k, v = mk(1, 2, 100, 2, 16), mk(1, 2, 100, 2, 16)
        out = pallas_evoformer_attention(q, k, v, interpret=True)
        want = reference_evoformer_attention(q, k, v)
        np.testing.assert_allclose(out, want, atol=2e-5, rtol=2e-5)
