"""Kernel numerics vs reference (reference analog: tests/unit/ops/* —
kernel-vs-torch numerics). Pallas kernels run in interpret mode on CPU, so
the same code path that compiles on TPU is validated here."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hcache_deepspeed_tpu.ops import get_op_impl, op_report
from hcache_deepspeed_tpu.ops.flash_attention import (pallas_attention,
                                                      reference_attention)
from hcache_deepspeed_tpu.ops.quantizer import (pallas_quantize,
                                                reference_dequantize,
                                                reference_quantize)
from hcache_deepspeed_tpu.ops.rms_norm import (pallas_rms_norm,
                                               reference_rms_norm)
from hcache_deepspeed_tpu.ops.rope import apply_rope, rope_frequencies


class TestFlashAttention:
    def _qkv(self, B=2, T=128, H=4, D=64, dtype=jnp.float32, seed=0):
        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        shape = (B, T, H, D)
        return tuple(jax.random.normal(k, shape, dtype) for k in ks)

    @pytest.mark.parametrize("causal", [True, False])
    def test_fwd_matches_reference(self, causal):
        q, k, v = self._qkv()
        ref = reference_attention(q, k, v, causal=causal)
        got = pallas_attention(q, k, v, causal=causal, block_q=64,
                               block_k=64, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3)

    def test_bwd_matches_reference(self):
        q, k, v = self._qkv(B=1, T=128, H=2, D=32)

        def loss_ref(q, k, v):
            return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

        def loss_pl(q, k, v):
            return jnp.sum(pallas_attention(q, k, v, causal=True,
                                            block_q=64, block_k=64,
                                            interpret=True) ** 2)

        ref_grads = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        got_grads = jax.grad(loss_pl, argnums=(0, 1, 2))(q, k, v)
        for g, r in zip(got_grads, ref_grads):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                       rtol=5e-3, atol=5e-3)

    def test_non_divisible_falls_back(self):
        q, k, v = self._qkv(T=100)
        out = pallas_attention(q, k, v, interpret=True)
        ref = reference_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3)


class TestRMSNorm:
    def test_fwd(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 64, 256))
        w = jax.random.normal(jax.random.PRNGKey(1), (256,)) + 1.0
        ref = reference_rms_norm(x, w)
        got = pallas_rms_norm(x, w, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_bwd(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (8, 128))
        w = jax.random.normal(jax.random.PRNGKey(1), (128,)) + 1.0

        ref = jax.grad(lambda x, w: jnp.sum(reference_rms_norm(x, w) ** 2),
                       argnums=(0, 1))(x, w)
        got = jax.grad(
            lambda x, w: jnp.sum(pallas_rms_norm(x, w, interpret=True) ** 2),
            argnums=(0, 1))(x, w)
        for g, r in zip(got, ref):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                       rtol=1e-4, atol=1e-4)


class TestRope:
    def test_rotation_preserves_norm(self):
        cos, sin = rope_frequencies(64, 128)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 4, 64))
        out = apply_rope(x, cos, sin)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(out), axis=-1),
            np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-5)

    def test_position_zero_identity(self):
        cos, sin = rope_frequencies(32, 8)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 1, 2, 32))
        out = apply_rope(x, cos, sin, positions=jnp.zeros((1, 1), jnp.int32))
        np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-6)

    def test_relative_property(self):
        # <rope(q,m), rope(k,n)> depends only on m-n
        cos, sin = rope_frequencies(32, 64)
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 1, 1, 32))
        k = jax.random.normal(jax.random.PRNGKey(1), (1, 1, 1, 32))
        def dot_at(m, n):
            qm = apply_rope(q, cos, sin, jnp.full((1, 1), m, jnp.int32))
            kn = apply_rope(k, cos, sin, jnp.full((1, 1), n, jnp.int32))
            return float(jnp.sum(qm * kn))
        assert abs(dot_at(5, 3) - dot_at(10, 8)) < 1e-4


class TestQuantizer:
    @pytest.mark.parametrize("num_bits", [8, 4])
    def test_roundtrip_error_bounded(self, num_bits):
        x = jax.random.normal(jax.random.PRNGKey(0), (1000,))
        q, s, shape, n = reference_quantize(x, group_size=256,
                                            num_bits=num_bits)
        out = reference_dequantize(q, s, shape, n)
        err = np.abs(np.asarray(out) - np.asarray(x)).max()
        step = np.abs(np.asarray(x)).max() / (2 ** (num_bits - 1) - 1)
        assert err <= step

    def test_pallas_matches_reference(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4096,))
        q1, s1, _, _ = reference_quantize(x, group_size=256)
        q2, s2, _, _ = pallas_quantize(x, group_size=256, interpret=True)
        np.testing.assert_array_equal(np.asarray(q1), np.asarray(q2))
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-6)


class TestRegistry:
    def test_report(self):
        report = op_report()
        assert "flash_attention" in report

    def test_cpu_uses_reference(self):
        impl = get_op_impl("flash_attention")
        assert not impl.compatible()  # CPU: pallas not native
        assert impl.best() is impl.reference_fn
