"""Native C++ op tests.

Reference analogs: ``tests/unit/ops/aio/test_aio.py`` (read/write parity,
async submit/wait) and ``tests/unit/ops/adam/test_cpu_adam.py`` (SIMD
Adam vs reference numerics).
"""

import numpy as np
import pytest

from hcache_deepspeed_tpu.ops.native import (AsyncIOBuilder, AsyncIOHandle,
                                             CPUAdam, CPUAdamBuilder,
                                             CPULion)


@pytest.fixture(scope="module")
def aio():
    builder = AsyncIOBuilder()
    if not builder.is_compatible():
        pytest.skip("no g++ toolchain")
    return AsyncIOHandle(num_threads=2)


class TestAsyncIO:

    def test_write_read_roundtrip(self, aio, tmp_path):
        data = np.random.default_rng(0).standard_normal(
            1 << 16).astype(np.float32)
        path = str(tmp_path / "blob.bin")
        n = aio.sync_pwrite(data, path)
        assert n == data.nbytes
        out = np.empty_like(data)
        assert aio.sync_pread(out, path) == data.nbytes
        np.testing.assert_array_equal(out, data)

    def test_async_overlap(self, aio, tmp_path):
        rng = np.random.default_rng(1)
        bufs = [rng.standard_normal(1 << 14).astype(np.float32)
                for _ in range(8)]
        rids = [aio.async_pwrite(b, str(tmp_path / f"f{i}.bin"))
                for i, b in enumerate(bufs)]
        for rid in rids:
            aio.wait(rid)
        outs = [np.empty_like(b) for b in bufs]
        rids = [aio.async_pread(o, str(tmp_path / f"f{i}.bin"))
                for i, o in enumerate(outs)]
        for rid in rids:
            aio.wait(rid)
        for o, b in zip(outs, bufs):
            np.testing.assert_array_equal(o, b)

    def test_offset_io(self, aio, tmp_path):
        path = str(tmp_path / "off.bin")
        a = np.arange(64, dtype=np.float32)
        b = np.arange(64, 128, dtype=np.float32)
        aio.sync_pwrite(a, path, offset=0)
        aio.sync_pwrite(b, path, offset=a.nbytes)
        out = np.empty(128, np.float32)
        aio.sync_pread(out, path)
        np.testing.assert_array_equal(out, np.arange(128, dtype=np.float32))

    def test_missing_file_error(self, aio, tmp_path):
        out = np.empty(16, np.float32)
        with pytest.raises(OSError):
            aio.wait(aio.async_pread(out, str(tmp_path / "nope.bin")))


def _ref_adamw(p, g, m, v, lr, b1, b2, eps, wd, step):
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    mhat = m / (1 - b1 ** step)
    vhat = v / (1 - b2 ** step)
    p = p - lr * (mhat / (np.sqrt(vhat) + eps) + wd * p)
    return p, m, v


class TestCPUAdam:

    @pytest.fixture(scope="class")
    def lib(self):
        builder = CPUAdamBuilder()
        if not builder.is_compatible():
            pytest.skip("no g++ toolchain")
        return builder.load()

    @pytest.mark.parametrize("n", [7, 1024, 100_001])
    def test_matches_reference(self, lib, n):
        rng = np.random.default_rng(0)
        p = rng.standard_normal(n).astype(np.float32)
        ref_p = p.copy()
        m = np.zeros(n, np.float32)
        v = np.zeros(n, np.float32)
        ref_m, ref_v = m.copy(), v.copy()
        opt = CPUAdam(lr=1e-2, betas=(0.9, 0.95), eps=1e-8,
                      weight_decay=0.01)
        for step in range(1, 4):
            g = rng.standard_normal(n).astype(np.float32)
            opt.step(p, g.copy(), m, v)
            ref_p, ref_m, ref_v = _ref_adamw(ref_p, g, ref_m, ref_v,
                                             1e-2, 0.9, 0.95, 1e-8, 0.01,
                                             step)
            np.testing.assert_allclose(p, ref_p, rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(m, ref_m, rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(v, ref_v, rtol=2e-5, atol=2e-6)

    def test_matches_device_optimizer(self, lib):
        """Host SIMD step == the engine's device adamw (optax semantics)."""
        import jax
        import jax.numpy as jnp

        from hcache_deepspeed_tpu.runtime.optimizers import build_optimizer
        n = 512
        rng = np.random.default_rng(2)
        p0 = rng.standard_normal(n).astype(np.float32)
        g0 = rng.standard_normal(n).astype(np.float32)

        opt_def = build_optimizer("adamw", {"lr": 1e-3, "betas": [0.9, 0.999],
                                            "eps": 1e-8,
                                            "weight_decay": 0.0})
        state = opt_def.init({"w": jnp.asarray(p0)})
        updates, state = opt_def.update({"w": jnp.asarray(g0)}, state,
                                        {"w": jnp.asarray(p0)},
                                        jnp.float32(1e-3))
        dev_p = np.asarray(jnp.asarray(p0) + updates["w"])

        host_p, m, v = p0.copy(), np.zeros(n, np.float32), \
            np.zeros(n, np.float32)
        CPUAdam(lr=1e-3, betas=(0.9, 0.999), eps=1e-8).step(
            host_p, g0.copy(), m, v)
        np.testing.assert_allclose(host_p, dev_p, rtol=1e-5, atol=1e-6)

    def test_lion(self, lib):
        n = 256
        rng = np.random.default_rng(3)
        p = rng.standard_normal(n).astype(np.float32)
        m = np.zeros(n, np.float32)
        g = rng.standard_normal(n).astype(np.float32)
        p0 = p.copy()
        CPULion(lr=1e-3, betas=(0.9, 0.99)).step(p, g.copy(), m)
        c = 0.9 * 0 + 0.1 * g
        np.testing.assert_allclose(p, p0 - 1e-3 * np.sign(c), rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(m, 0.01 * g, rtol=1e-4, atol=1e-6)
