"""Ragged paged-attention kernel vs the dense-gather oracle
(reference analog: tests for inference/v2 kernels/ragged_ops/blocked_flash)."""

import numpy as np
import pytest

import jax.numpy as jnp

from hcache_deepspeed_tpu.ops.paged_attention import (
    pallas_paged_attention, reference_paged_attention)


def _case(B, T, Hq, KV, D, BS, NBLK, NB, starts, lens, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((B, T, Hq, D)), jnp.float32)
    kp = jnp.asarray(rng.standard_normal((KV, NBLK * BS, D)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((KV, NBLK * BS, D)), jnp.float32)
    perm = rng.permutation(NBLK)
    tables = perm[:B * NB].reshape(B, NB).astype(np.int32)
    start = jnp.asarray(starts, jnp.int32)
    kvl = jnp.asarray(lens, jnp.int32)
    ref = reference_paged_attention(q, kp, vp, tables, start, kvl, BS)
    pal = pallas_paged_attention(q, kp, vp, tables, start, kvl, BS,
                                 interpret=True)
    np.testing.assert_allclose(np.asarray(pal), np.asarray(ref), atol=3e-5)


class TestPagedAttentionParity:
    def test_ragged_decode_batch(self):
        # T=1 rows, wildly different context lengths in one batch
        _case(4, 1, 8, 2, 64, 16, 64, 8,
              starts=[0, 5, 33, 100], lens=[1, 6, 34, 101])

    def test_prefill_from_scratch(self):
        _case(1, 32, 8, 8, 64, 16, 16, 4, starts=[0], lens=[32])

    def test_chunked_prefill_continuation(self):
        # start > 0: continuation chunk attends to earlier cache blocks
        _case(1, 16, 4, 2, 32, 8, 32, 8, starts=[24], lens=[40])

    def test_mha_no_gqa(self):
        _case(2, 1, 4, 4, 128, 16, 32, 4, starts=[7, 0], lens=[8, 1])

    def test_single_token_context(self):
        _case(1, 1, 2, 2, 32, 8, 8, 2, starts=[0], lens=[1])

    def test_bf16(self):
        rng = np.random.default_rng(3)
        B, T, Hq, KV, D, BS, NBLK, NB = 2, 1, 4, 2, 64, 16, 16, 4
        q = jnp.asarray(rng.standard_normal((B, T, Hq, D)), jnp.bfloat16)
        kp = jnp.asarray(rng.standard_normal((KV, NBLK * BS, D)),
                         jnp.bfloat16)
        vp = jnp.asarray(rng.standard_normal((KV, NBLK * BS, D)),
                         jnp.bfloat16)
        tables = rng.permutation(NBLK)[:B * NB].reshape(B, NB).astype(
            np.int32)
        start = jnp.asarray([3, 17], jnp.int32)
        kvl = jnp.asarray([4, 18], jnp.int32)
        ref = reference_paged_attention(q, kp, vp, tables, start, kvl, BS)
        pal = pallas_paged_attention(q, kp, vp, tables, start, kvl, BS,
                                     interpret=True)
        np.testing.assert_allclose(
            np.asarray(pal, np.float32), np.asarray(ref, np.float32),
            atol=3e-2)

    def test_garbage_in_dead_table_slots_ignored(self):
        # dead table slots point at blocks full of huge values; the
        # clamped index_map + masking must never read them into the result
        rng = np.random.default_rng(4)
        B, T, Hq, KV, D, BS, NBLK, NB = 1, 1, 2, 2, 32, 8, 16, 8
        q = jnp.asarray(rng.standard_normal((B, T, Hq, D)), jnp.float32)
        kp = rng.standard_normal((KV, NBLK * BS, D)).astype(np.float32)
        vp = rng.standard_normal((KV, NBLK * BS, D)).astype(np.float32)
        kp[:, BS * 2:], vp[:, BS * 2:] = 1e9, 1e9  # poison all but blocks 0-1
        tables = np.zeros((B, NB), np.int32)
        tables[0, 0], tables[0, 1] = 0, 1
        tables[0, 2:] = 9  # dead slots point at poison
        start = jnp.asarray([11], jnp.int32)
        kvl = jnp.asarray([12], jnp.int32)  # only blocks 0-1 valid
        pal = pallas_paged_attention(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp), tables,
            start, kvl, BS, interpret=True)
        assert np.all(np.isfinite(np.asarray(pal)))
        assert np.max(np.abs(np.asarray(pal))) < 1e3


class TestHeadTiling:
    """KVT kv heads per grid step (the decode-shape grid-count fix) must
    be invisible to results for every tile size."""

    @pytest.mark.parametrize("head_tile", [1, 2, 4, 0])   # 0 = adaptive
    def test_tile_sizes_agree(self, head_tile):
        rng = np.random.default_rng(5)
        B, T, Hq, KV, D, BS, NBLK, NB = 3, 1, 8, 4, 64, 16, 32, 8
        q = jnp.asarray(rng.standard_normal((B, T, Hq, D)), jnp.float32)
        kp = jnp.asarray(rng.standard_normal((KV, NBLK * BS, D)),
                         jnp.float32)
        vp = jnp.asarray(rng.standard_normal((KV, NBLK * BS, D)),
                         jnp.float32)
        tables = rng.permutation(NBLK)[:B * NB].reshape(B, NB).astype(
            np.int32)
        start = jnp.asarray([0, 40, 99], jnp.int32)
        kvl = jnp.asarray([1, 41, 100], jnp.int32)
        ref = reference_paged_attention(q, kp, vp, tables, start, kvl, BS)
        pal = pallas_paged_attention(q, kp, vp, tables, start, kvl, BS,
                                     interpret=True, head_tile=head_tile)
        np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                                   atol=3e-5)

    def test_pick_head_tile(self):
        from hcache_deepspeed_tpu.ops.paged_attention import \
            _pick_head_tile
        # decode shapes fit every head in one step
        assert _pick_head_tile(32, 8, 64, 64, 2) == 32
        # must divide KV
        assert 24 % _pick_head_tile(24, 8, 64, 64, 2) == 0
        # large prefill tiles shrink under the budget but stay >= 1
        kvt = _pick_head_tile(32, 512, 128, 64, 2)
        assert 1 <= kvt <= 32 and 32 % kvt == 0
        per_head = (2 * 512 * 128 * 2 + 2 * 2 * 64 * 128 * 2
                    + 512 * 128 * 4 + 2 * 512 * 128 * 4)
        assert kvt * per_head <= 6 * 2**20

    def test_non_divisor_head_tile_rejected(self):
        rng = np.random.default_rng(6)
        B, T, Hq, KV, D, BS, NBLK, NB = 1, 1, 4, 4, 32, 8, 8, 2
        q = jnp.asarray(rng.standard_normal((B, T, Hq, D)), jnp.float32)
        kp = jnp.asarray(rng.standard_normal((KV, NBLK * BS, D)),
                         jnp.float32)
        vp = jnp.asarray(rng.standard_normal((KV, NBLK * BS, D)),
                         jnp.float32)
        tables = np.zeros((B, NB), np.int32)
        with pytest.raises(ValueError, match="head_tile"):
            pallas_paged_attention(q, kp, vp, tables,
                                   jnp.asarray([0], jnp.int32),
                                   jnp.asarray([1], jnp.int32), BS,
                                   interpret=True, head_tile=3)
