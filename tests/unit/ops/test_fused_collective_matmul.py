"""Fused gather-matmul / reduce-scatter-epilogue kernels (ISSUE 18).

The contract under test is the transport-swap twin discipline:

- ``reference_fused_gather_matmul`` is BITWISE-equal to the unfused
  gather-then-matmul pipeline (ring gathers are pure data movement,
  the consumption kernel is shared) for both shard layouts;
- the ``streamed`` schedule (the in-flight ring form the Pallas kernel
  realizes) is value-equal — chunked K-summation reorders fp32
  accumulation, never semantics;
- the resident-chunk Pallas kernel (interpret mode) matches the same
  oracle — it runs the ring kernel's exact compute schedule with the
  transport swapped for HBM chunks;
- layout guards fall back to the reference twin LOUDLY
  (``fused_fallback_debug_info``);
- ``fused_qrs_exchange`` is bitwise-equal to the native ``all_to_all``
  it replaces, and the fused quant+EF epilogue matches the host twin
  under jit (the engine always runs jitted).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from hcache_deepspeed_tpu.ops.fused_collective_matmul import (
    ShardedQuantizedTensor, fused_fallback_debug_info,
    fused_qrs_exchange, pallas_fused_gather_matmul,
    pallas_fused_gather_matmul_resident, reference_fused_gather_matmul,
    streamed_fused_gather_matmul)
from hcache_deepspeed_tpu.ops.quantized_matmul import (
    quantize_for_matmul, quantized_matmul)
from hcache_deepspeed_tpu.parallel.topology import DATA_AXIS


def _shmap(fn, in_specs, out_specs):
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), (DATA_AXIS,))
    return jax.jit(functools.partial(
        jax.shard_map, mesh=mesh, axis_names={DATA_AXIS},
        in_specs=in_specs, out_specs=out_specs, check_vma=False)(fn))


def _mk(K=64, N=16, M=4, group_k=8, seed=0):
    rng = np.random.default_rng(seed)
    w = jnp.asarray(rng.normal(size=(K, N)), jnp.float32)
    q, s = quantize_for_matmul(w, group_k)
    x = jnp.asarray(rng.normal(size=(M, K)), jnp.float32)
    return x, q, s


def _unfused(x, q_sh, s_sh, dim, group_k):
    """The unfused pipeline: native gather, assemble, shared matmul."""
    def asm(sh):
        per = jax.lax.all_gather(sh, DATA_AXIS)
        parts = jnp.moveaxis(per, 0, dim)
        shape = sh.shape[:dim] + (-1,) + sh.shape[dim + 1:]
        return parts.reshape(shape)
    return quantized_matmul(x, asm(q_sh), asm(s_sh), group_k=group_k)


class TestGatherMatmulTwins:

    @pytest.mark.parametrize("dim", [0, 1])
    def test_reference_bitwise_vs_unfused(self, eight_devices, dim):
        x, q, s = _mk()

        def fused(q_sh, s_sh):
            return reference_fused_gather_matmul(
                x, q_sh, s_sh, group_k=8, axis_name=DATA_AXIS,
                shard_dim=dim)

        def unfused(q_sh, s_sh):
            return _unfused(x, q_sh, s_sh, dim, 8)

        specs = (P(DATA_AXIS), P(DATA_AXIS)) if dim == 0 else \
            (P(None, DATA_AXIS), P(None, DATA_AXIS))
        a = _shmap(fused, specs, P())(q, s)
        b = _shmap(unfused, specs, P())(q, s)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.parametrize("dim", [0, 1])
    def test_streamed_value_equal(self, eight_devices, dim):
        x, q, s = _mk()

        def streamed(q_sh, s_sh):
            return streamed_fused_gather_matmul(
                x, q_sh, s_sh, group_k=8, axis_name=DATA_AXIS,
                shard_dim=dim)

        def unfused(q_sh, s_sh):
            return _unfused(x, q_sh, s_sh, dim, 8)

        specs = (P(DATA_AXIS), P(DATA_AXIS)) if dim == 0 else \
            (P(None, DATA_AXIS), P(None, DATA_AXIS))
        a = _shmap(streamed, specs, P())(q, s)
        b = _shmap(unfused, specs, P())(q, s)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)

    def test_resident_kernel_interpret_matches_oracle(self):
        """The interpret-mode-testable half of the kernel pair: chunked
        resident schedule vs the shared whole-matrix kernel."""
        x, q, s = _mk(K=512, N=128, M=16, group_k=32, seed=3)
        m, k_sh = 4, 128
        q_all = q.reshape(m, k_sh, 128)
        s_all = s.reshape(m, k_sh // 32, 128)
        out = pallas_fused_gather_matmul_resident(
            x, q_all, s_all, group_k=32, interpret=True)
        ref = quantized_matmul(x, q, s, group_k=32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-3, rtol=1e-3)


class TestFallbacks:

    def test_unsupported_layout_falls_back_loudly(self, eight_devices):
        """N-sharded (shard_dim=1) rides the reference twin — counted,
        reason recorded, result still bitwise vs the unfused pipeline."""
        x, q, s = _mk(seed=4)
        before = fused_fallback_debug_info()["count"]

        def fused(q_sh, s_sh):
            return pallas_fused_gather_matmul(
                x, q_sh, s_sh, group_k=8, axis_name=DATA_AXIS,
                shard_dim=1)

        def unfused(q_sh, s_sh):
            return _unfused(x, q_sh, s_sh, 1, 8)

        specs = (P(None, DATA_AXIS), P(None, DATA_AXIS))
        a = _shmap(fused, specs, P())(q, s)
        b = _shmap(unfused, specs, P())(q, s)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        info = fused_fallback_debug_info()
        assert info["count"] > before
        assert info["by_reason"].get("unsupported_layout", 0) >= 1
        assert info["warned"] is True
        assert info["last"][0] == "unsupported_layout"


class TestShardedQuantizedTensor:

    def test_pytree_roundtrip_keeps_static_coords(self):
        _, q, s = _mk()
        sqt = ShardedQuantizedTensor(q[:8], s[:1], 8, 0, DATA_AXIS,
                                     groups=[[0, 1], [2, 3]])
        leaves, treedef = jax.tree.flatten(sqt)
        back = jax.tree.unflatten(treedef, leaves)
        assert isinstance(back, ShardedQuantizedTensor)
        assert back.group_k == 8 and back.dim == 0
        assert back.axis_name == DATA_AXIS
        assert back.groups == ((0, 1), (2, 3))
        np.testing.assert_array_equal(np.asarray(back.q),
                                      np.asarray(q[:8]))

    def test_matmul_and_gather_bitwise(self, eight_devices):
        x, q, s = _mk(seed=5)

        def via_tensor(q_sh, s_sh):
            sqt = ShardedQuantizedTensor(q_sh, s_sh, 8, 0, DATA_AXIS)
            full = sqt.gather()
            return sqt.matmul(x), full.q, full.scale

        def unfused(q_sh, s_sh):
            return _unfused(x, q_sh, s_sh, 0, 8)

        specs = (P(DATA_AXIS), P(DATA_AXIS))
        y, qf, sf = _shmap(via_tensor, specs, (P(), P(), P()))(q, s)
        b = _shmap(unfused, specs, P())(q, s)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(b))
        # the backward-recompute gather reassembles the exact bits
        np.testing.assert_array_equal(np.asarray(qf), np.asarray(q))
        np.testing.assert_array_equal(np.asarray(sf), np.asarray(s))


class TestReduceScatterEpilogue:

    def test_qrs_exchange_bitwise_vs_all_to_all(self, eight_devices):
        rng = np.random.default_rng(6)
        pay = jnp.asarray(rng.integers(-127, 128, (8, 8, 6)), jnp.int8)
        sc = jnp.asarray(rng.normal(size=(8, 8, 2)), jnp.float32)

        def fused(p, s):
            return fused_qrs_exchange(p[0], s[0], axis_name=DATA_AXIS)

        def native(p, s):
            return (jax.lax.all_to_all(p[0], DATA_AXIS, 0, 0),
                    jax.lax.all_to_all(s[0], DATA_AXIS, 0, 0))

        specs = (P(DATA_AXIS), P(DATA_AXIS))
        outs = (P(DATA_AXIS), P(DATA_AXIS))
        fp, fs = _shmap(fused, specs, outs)(pay, sc)
        npay, ns = _shmap(native, specs, outs)(pay, sc)
        np.testing.assert_array_equal(np.asarray(fp), np.asarray(npay))
        np.testing.assert_array_equal(np.asarray(fs), np.asarray(ns))

    def test_fused_quant_ef_matches_host_twin_under_jit(self):
        """The engine always runs jitted; under jit the fused Pallas
        epilogue (interpret mode here) is bitwise-equal to the host
        twin — same quantize / dequantize / subtract trio."""
        from hcache_deepspeed_tpu.ops.fused_collective_matmul import (
            pallas_fused_quant_ef, reference_fused_quant_ef)
        rng = np.random.default_rng(7)
        wide = jnp.asarray(rng.normal(size=(4, 64)), jnp.float32)
        res = jnp.asarray(rng.normal(size=(4, 64)) * 0.01, jnp.float32)
        ref = jax.jit(functools.partial(
            reference_fused_quant_ef, group_size=16))(wide, res)
        out = jax.jit(functools.partial(
            pallas_fused_quant_ef, group_size=16,
            interpret=True))(wide, res)
        for a, b in zip(out, ref):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
