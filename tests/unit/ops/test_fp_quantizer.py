"""FP8/FP6 group quantizer (reference: csrc/fp_quantizer/fp_quantize.cu)."""

import jax.numpy as jnp
import numpy as np
import pytest

from hcache_deepspeed_tpu.ops.fp_quantizer import (
    dequantize_fp6, dequantize_fp8, pallas_quantize_fp8,
    reference_quantize_fp6, reference_quantize_fp8, selective_dequantize)


def _x(shape, seed=0, scale=3.0):
    return jnp.asarray(
        np.random.default_rng(seed).standard_normal(shape) * scale,
        jnp.float32)


class TestFP8:
    @pytest.mark.parametrize("fmt,rtol", [("e4m3", 0.08), ("e5m2", 0.2)])
    def test_roundtrip_error_bound(self, fmt, rtol):
        x = _x((64, 256))
        q, s, shape, n = reference_quantize_fp8(x, 256, fmt)
        assert q.dtype == (jnp.float8_e4m3fn if fmt == "e4m3"
                           else jnp.float8_e5m2)
        out = dequantize_fp8(q, s, shape, n)
        err = np.abs(np.asarray(out) - np.asarray(x))
        # per-group max sets the scale; elementwise error ≤ grid step
        assert np.max(err / (np.abs(np.asarray(x)) + 1e-3)) < rtol * 4
        assert np.mean(err) < rtol * np.mean(np.abs(np.asarray(x)))

    def test_pallas_matches_reference(self):
        x = _x((32, 512), seed=1)
        qr, sr, shr, nr = reference_quantize_fp8(x, 256)
        qp, sp, shp, np_ = pallas_quantize_fp8(x, 256, interpret=True)
        # reduction order differs → scales agree to float assoc. noise;
        # compare the dequantized values
        np.testing.assert_allclose(np.asarray(sr), np.asarray(sp),
                                   rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(dequantize_fp8(qr, sr, shr, nr)),
            np.asarray(dequantize_fp8(qp, sp, shp, np_)), rtol=2e-2,
            atol=1e-3)

    def test_zero_tensor(self):
        x = jnp.zeros((4, 256))
        q, s, shape, n = reference_quantize_fp8(x, 256)
        np.testing.assert_array_equal(
            np.asarray(dequantize_fp8(q, s, shape, n)), 0.0)

    def test_padding_tail(self):
        x = _x((3, 100))  # 300 elems, group 256 → padded
        q, s, shape, n = reference_quantize_fp8(x, 256)
        out = dequantize_fp8(q, s, shape, n)
        assert out.shape == x.shape


class TestFP6:
    def test_roundtrip_error_bound(self):
        x = _x((16, 256), seed=2)
        q, s, shape, n = reference_quantize_fp6(x, 256)
        assert q.dtype == jnp.uint8
        out = dequantize_fp6(q, s, shape, n)
        err = np.abs(np.asarray(out) - np.asarray(x))
        xs = np.abs(np.asarray(x))
        # E3M2: 2 mantissa bits → ≤ 12.5% relative on normals; near-zero
        # values bottom out at the subnormal step (scale * 2^-2 / 4)
        scale_max = float(np.max(np.asarray(s)))
        assert np.max(err) < scale_max * 2.01  # half max grid spacing
        normal = xs > scale_max  # comfortably in the normal range
        assert np.max((err / np.maximum(xs, 1e-9))[normal]) < 0.13
        assert np.mean(err / (xs + 1e-2)) < 0.08

    def test_exact_grid_values(self):
        # values on the E3M2 grid (scaled so max maps to 28) roundtrip
        vals = jnp.asarray([[0.0, 1.0, 1.25, 1.5, 1.75, 2.0, -3.5, 28.0]])
        q, s, shape, n = reference_quantize_fp6(vals, 8)
        out = np.asarray(dequantize_fp6(q, s, shape, n))
        np.testing.assert_allclose(out, np.asarray(vals), rtol=1e-6)

    def test_code_range_is_6_bits(self):
        x = _x((8, 256), seed=3)
        q, _, _, _ = reference_quantize_fp6(x, 256)
        assert int(np.max(np.asarray(q))) < 64


class TestSelectiveDequant:
    def test_rows_match_full(self):
        x = _x((16, 128), seed=4)
        q, s, shape, n = reference_quantize_fp8(x, 128)
        full = np.asarray(dequantize_fp8(q, s, shape, n))
        sel = np.asarray(selective_dequantize(q, s, shape, n,
                                              np.asarray([2, 5, 11])))
        np.testing.assert_allclose(sel, full[[2, 5, 11]])

    def test_fp6_rows(self):
        x = _x((8, 128), seed=5)
        q, s, shape, n = reference_quantize_fp6(x, 128)
        full = np.asarray(dequantize_fp6(q, s, shape, n))
        sel = np.asarray(selective_dequantize(q, s, shape, n,
                                              slice(1, 4)))
        np.testing.assert_allclose(sel, full[1:4])

    def test_misaligned_rows_rejected(self):
        x = _x((4, 100), seed=6)
        q, s, shape, n = reference_quantize_fp8(x, 64)
        with pytest.raises(ValueError, match="aligned"):
            selective_dequantize(q, s, shape, n, slice(0, 2))
