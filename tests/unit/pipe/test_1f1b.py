"""Interleaved 1F1B executor (reference: pipe/engine.py:1409 _exec_schedule
over schedule.py:189 TrainSchedule): loss/grad parity with the GPipe
executor, the peak_in_flight memory bound, and closed-form tick timing vs
the TrainSchedule enumeration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hcache_deepspeed_tpu.models.gpt2 import (gpt2_pipeline_layers,
                                              gpt2_tiny)
from hcache_deepspeed_tpu.parallel import topology as topo_mod
from hcache_deepspeed_tpu.runtime.pipe.module import PipelineModule
from hcache_deepspeed_tpu.runtime.pipe import schedule as sched


@pytest.fixture
def pipe_topo(eight_devices):
    topo = topo_mod.initialize_topology(
        topo_mod.TopologySpec(pipe=4, data=2))
    yield topo
    topo_mod.reset_topology()


def _batch(n, seq=32, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, 256, (n, seq), dtype=np.int32)}


def _modules(topo, M, seq=32, n_layer=4):
    cfg = gpt2_tiny(n_layer=n_layer, n_positions=seq)
    layers, loss_fn = gpt2_pipeline_layers(cfg)
    m1 = PipelineModule(layers, loss_fn, topology=topo, n_microbatches=M,
                        schedule="1f1b")
    mg = PipelineModule(layers, loss_fn, topology=topo, n_microbatches=M,
                        schedule="gpipe")
    return m1, mg


class TestTickClosedForms:
    """The executor's F/B closed forms must agree with TrainSchedule."""

    @pytest.mark.parametrize("S,M", [(2, 2), (4, 4), (4, 8), (3, 5),
                                     (6, 12), (8, 16), (8, 32)])
    def test_fwd_bwd_ticks_match_enumeration(self, S, M):
        for s in range(S):
            steps = sched.TrainSchedule(M, S, s).steps()
            fwd_slots = {}
            bwd_slots = {}
            for t, cmds in enumerate(steps):
                for c in cmds:
                    if isinstance(c, sched.ForwardPass):
                        fwd_slots[c.micro_batch_id] = t
                    if isinstance(c, sched.BackwardPass):
                        bwd_slots[c.micro_batch_id] = t
            # the enumeration is per-stage-compacted; the global-clock
            # forms must preserve its ORDER and the 1F1B invariants
            fwd_order = sorted(fwd_slots, key=fwd_slots.get)
            bwd_order = sorted(bwd_slots, key=bwd_slots.get)
            f_ticks = [sched.fwd_tick(s, f, S) for f in range(M)]
            b_ticks = [sched.bwd_tick(s, b, S) for b in range(M)]
            assert fwd_order == sorted(range(M), key=lambda f: f_ticks[f])
            assert bwd_order == sorted(range(M), key=lambda b: b_ticks[b])
            # dependency sanity on the global clock
            for f in range(M):
                if s > 0:
                    assert sched.fwd_tick(s, f, S) > \
                        sched.fwd_tick(s - 1, f, S)
                assert sched.bwd_tick(s, f, S) > sched.fwd_tick(s, f, S) \
                    or s == S - 1  # last stage folds fwd into bwd
                if s < S - 1:
                    assert sched.bwd_tick(s, f, S) == \
                        sched.bwd_tick(s + 1, f, S) + 1
            assert max(b_ticks) < sched.one_f_one_b_ticks(M, S)
            # in-flight bound: fwds issued minus bwds done never exceeds
            # peak_in_flight
            peak = 0
            for t in range(sched.one_f_one_b_ticks(M, S)):
                live = sum(1 for f in range(M)
                           if f_ticks[f] <= t < b_ticks[f])
                peak = max(peak, live)
            assert peak <= sched.peak_in_flight(M, S, s)


class TestDeepPipeline:
    def test_s8_compiles_with_bounded_ring(self, eight_devices):
        """S=8 (every device a stage), M=32: the deep-pipeline shape
        where closed-form off-by-ones would bite. AOT-compile the full
        fwd+bwd program and assert the 1F1B ring bound holds: temp
        memory stays flat from M=8 to M=32 while GPipe's would scale
        4x. The compiled program's ppermute/tick structure is recorded
        in docs/parallelism.md."""
        topo = topo_mod.initialize_topology(
            topo_mod.TopologySpec(pipe=8, data=1))
        try:
            def temp_bytes(M):
                batch = _batch(M, seq=32)
                cfg = gpt2_tiny(n_layer=8, n_positions=32)
                layers, loss_fn = gpt2_pipeline_layers(cfg)
                mod = PipelineModule(layers, loss_fn, topology=topo,
                                     n_microbatches=M, schedule="1f1b")
                params = mod.init_params(jax.random.PRNGKey(0), batch)
                f = jax.jit(jax.value_and_grad(
                    lambda p: mod(p, batch, None, True)))
                compiled = f.lower(params).compile()
                txt = compiled.as_text()
                # the ring exists: stage-boundary transfers compile to
                # collective-permutes inside the tick loop
                assert "collective-permute" in txt
                return compiled.memory_analysis().temp_size_in_bytes

            t8 = temp_bytes(8)
            t32 = temp_bytes(32)
            # peak_in_flight(M,S=8,stage0) == 8 for both: flat temp
            assert t32 < t8 * 1.3, (t8, t32)
        finally:
            topo_mod.reset_topology()


class TestParity:
    def test_loss_and_grads_match_gpipe(self, pipe_topo):
        m1, mg = _modules(pipe_topo, M=4)
        batch = _batch(8)
        params = m1.init_params(jax.random.PRNGKey(0), batch)
        l1 = jax.jit(lambda p: m1(p, batch, None, True))(params)
        lg = jax.jit(lambda p: mg(p, batch, None, True))(params)
        assert abs(float(l1) - float(lg)) < 1e-5
        g1 = jax.jit(jax.grad(lambda p: m1(p, batch, None, True)))(params)
        gg = jax.jit(jax.grad(lambda p: mg(p, batch, None, True)))(params)
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(gg)):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                atol=1e-5)

    def test_uneven_warmup_m_gt_s(self, pipe_topo):
        m1, mg = _modules(pipe_topo, M=8)
        batch = _batch(16, seed=3)
        params = m1.init_params(jax.random.PRNGKey(1), batch)
        l1 = jax.jit(lambda p: m1(p, batch, None, True))(params)
        lg = jax.jit(lambda p: mg(p, batch, None, True))(params)
        assert abs(float(l1) - float(lg)) < 1e-5


class TestMemoryBound:
    def test_temp_memory_flat_in_microbatches(self, pipe_topo):
        """1F1B: per-stage live activations bounded by peak_in_flight, so
        compiled temp memory must NOT scale with M (GPipe's does)."""

        def temp_bytes(schedule, M):
            batch = _batch(2 * M, seq=128)
            cfg = gpt2_tiny(n_layer=4, n_positions=128)
            layers, loss_fn = gpt2_pipeline_layers(cfg)
            mod = PipelineModule(layers, loss_fn, topology=pipe_topo,
                                 n_microbatches=M, schedule=schedule)
            params = mod.init_params(jax.random.PRNGKey(0), batch)
            f = jax.jit(jax.value_and_grad(
                lambda p: mod(p, batch, None, True)))
            return f.lower(params).compile().memory_analysis() \
                .temp_size_in_bytes

        t4 = temp_bytes("1f1b", 4)
        t16 = temp_bytes("1f1b", 16)
        assert t16 < t4 * 1.3, (t4, t16)  # flat (ring buffer, not M)
        g16 = temp_bytes("gpipe", 16)
        assert t16 < g16 / 4, (t16, g16)  # and far below GPipe at M=16


class TestEngine1F1B:
    def test_pipeline_engine_trains_1f1b(self, pipe_topo):
        import hcache_deepspeed_tpu as hds
        cfg = gpt2_tiny(n_layer=4)
        layers, loss_fn = gpt2_pipeline_layers(cfg)
        module = PipelineModule(layers, loss_fn, topology=pipe_topo)
        config = {
            "train_batch_size": 16,
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 4,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "pipeline": {"schedule": "1f1b"},
        }
        engine, _, _, _ = hds.initialize(
            model=module, config=config, example_batch=_batch(16),
            topology=pipe_topo)
        batch = _batch(16, seed=5)
        losses = [float(engine.train_batch(batch=batch)) for _ in range(5)]
        assert losses[-1] < losses[0]
