"""Flat → pipeline param conversion (reference: loading a non-pipeline
checkpoint into a PipelineModule run via layer state files)."""

import jax
import numpy as np
import pytest

import hcache_deepspeed_tpu as hds
from hcache_deepspeed_tpu.models.gpt2 import (GPT2LMHeadModel,
                                              gpt2_flat_to_pipeline,
                                              gpt2_pipeline_layers,
                                              gpt2_tiny)
from hcache_deepspeed_tpu.parallel import topology as topo_mod
from hcache_deepspeed_tpu.runtime.pipe.module import PipelineModule


def _batch(n, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, 256, (n, seq), dtype=np.int32)}


class TestFlatToPipeline:
    def test_pipeline_matches_flat_model_and_trains(self, eight_devices):
        cfg = gpt2_tiny(n_layer=4)
        flat_model = GPT2LMHeadModel(cfg)
        flat = flat_model.init(jax.random.PRNGKey(0), _batch(1),
                               train=False)["params"]

        topo = topo_mod.initialize_topology(
            topo_mod.TopologySpec(pipe=2, data=4))
        layers, loss_fn = gpt2_pipeline_layers(cfg)
        module = PipelineModule(layers, loss_fn, topology=topo,
                                n_microbatches=2)
        pipe_params = gpt2_flat_to_pipeline(flat, cfg)

        engine, _, _, _ = hds.initialize(
            model=module, example_batch=_batch(1), topology=topo,
            init_params=pipe_params,
            config={"train_batch_size": 8,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "steps_per_print": 10 ** 9})

        batch = _batch(8)
        # forward parity: pipeline loss from converted params equals the
        # flat model's loss on the same batch
        want = float(flat_model.apply({"params": flat}, batch,
                                      train=False))
        got = float(engine.eval_batch(batch))
        np.testing.assert_allclose(got, want, rtol=1e-5)

        losses = [float(engine.train_batch(batch=batch))
                  for _ in range(4)]
        assert losses[-1] < losses[0]

    def test_missing_layers_rejected(self):
        cfg = gpt2_tiny(n_layer=2)
        with pytest.raises(ValueError, match="missing"):
            gpt2_flat_to_pipeline({"wte": {}}, cfg)

    def test_layer_count_mismatch_rejected(self):
        cfg = gpt2_tiny(n_layer=2)
        model = GPT2LMHeadModel(gpt2_tiny(n_layer=4))
        flat = model.init(jax.random.PRNGKey(0), _batch(1),
                          train=False)["params"]
        with pytest.raises(ValueError, match="beyond n_layer"):
            gpt2_flat_to_pipeline(flat, cfg)


class TestLlamaPipeline:
    def test_pipeline_matches_flat_model_and_trains(self, eight_devices):
        from hcache_deepspeed_tpu.models.llama import (
            LlamaForCausalLM, llama_flat_to_pipeline,
            llama_pipeline_layers, llama_tiny)
        cfg = llama_tiny(n_layer=4, use_flash=False)
        flat_model = LlamaForCausalLM(cfg)
        flat = flat_model.init(jax.random.PRNGKey(0), _batch(1),
                               train=False)["params"]

        topo = topo_mod.initialize_topology(
            topo_mod.TopologySpec(pipe=2, data=4))
        layers, loss_fn = llama_pipeline_layers(cfg)
        module = PipelineModule(layers, loss_fn, topology=topo,
                                n_microbatches=2)
        engine, _, _, _ = hds.initialize(
            model=module, example_batch=_batch(1), topology=topo,
            init_params=llama_flat_to_pipeline(flat, cfg),
            config={"train_batch_size": 8,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "steps_per_print": 10 ** 9})

        batch = _batch(8)
        want = float(flat_model.apply({"params": flat}, batch,
                                      train=False))
        got = float(engine.eval_batch(batch))
        np.testing.assert_allclose(got, want, rtol=1e-5)

        losses = [float(engine.train_batch(batch=batch))
                  for _ in range(4)]
        assert losses[-1] < losses[0]

    def test_tied_embeddings_rejected(self):
        from hcache_deepspeed_tpu.models.llama import (
            llama_pipeline_layers, llama_tiny)
        with pytest.raises(ValueError, match="untied"):
            llama_pipeline_layers(llama_tiny(tie_word_embeddings=True))


class TestPipeResizeResume:
    def test_checkpoint_resumes_at_different_pipe_degree(
            self, eight_devices, tmp_path):
        """The stacked-blocks layout is topology-free: a checkpoint
        trained at pipe=2 restores at pipe=4 (resharding-on-load) and
        continues training — the pipe axis of the universal-checkpoint
        reshape matrix (dp/tp/zero/EP are covered elsewhere)."""
        cfg = gpt2_tiny(n_layer=4)
        batch = _batch(8)

        def build(pipe, data):
            topo = topo_mod.initialize_topology(
                topo_mod.TopologySpec(pipe=pipe, data=data))
            layers, loss_fn = gpt2_pipeline_layers(cfg)
            module = PipelineModule(layers, loss_fn, topology=topo,
                                    n_microbatches=2)
            engine, _, _, _ = hds.initialize(
                model=module, example_batch=_batch(1), topology=topo,
                config={"train_batch_size": 8,
                        "optimizer": {"type": "Adam",
                                      "params": {"lr": 1e-3}},
                        "steps_per_print": 10 ** 9})
            return engine

        engine = build(pipe=2, data=4)
        losses = [float(engine.train_batch(batch=batch))
                  for _ in range(3)]
        engine.save_checkpoint(str(tmp_path), tag="t")
        ref = jax.tree.map(np.asarray, engine.state["params"])
        topo_mod.reset_topology()

        engine2 = build(pipe=4, data=2)
        engine2.load_checkpoint(str(tmp_path), tag="t")
        for a, b in zip(jax.tree.leaves(ref),
                        jax.tree.leaves(engine2.state["params"])):
            np.testing.assert_array_equal(a, np.asarray(b))
        l2 = float(engine2.train_batch(batch=batch))
        assert np.isfinite(l2) and l2 < losses[0]
