"""Synthetic schedule trace: the (tick, stage) work table rendered as
trace_event spans must validate and reproduce the 1F1B/GPipe tick
arithmetic the executor tests pin down."""

import pytest

from hcache_deepspeed_tpu.runtime.pipe.schedule import (
    bwd_tick, fwd_tick, gpipe_tick_work, schedule_trace_events)
from hcache_deepspeed_tpu.telemetry import validate_trace


def test_1f1b_trace_spans_match_tick_arithmetic():
    M, S = 4, 2
    events = schedule_trace_events(M, S, "1f1b", tick_us=100.0)
    assert validate_trace(events)["spans"] == 2 * M * S
    for ev in events:
        mb, s = ev["args"]["micro_batch"], ev["args"]["stage"]
        tick = (fwd_tick(s, mb, S) if ev["name"].startswith("pipe.fwd")
                else bwd_tick(s, mb, S))
        assert ev["ts"] == tick * 100.0 and ev["tid"] == s


def test_gpipe_trace_matches_work_table():
    M, S = 3, 3
    events = schedule_trace_events(M, S, "gpipe")
    table = gpipe_tick_work(M, S)
    expected = sum(1 for row in table for mb in row if mb is not None)
    assert validate_trace(events)["spans"] == expected == M * S


def test_unknown_schedule_rejected():
    with pytest.raises(ValueError, match="unknown schedule"):
        schedule_trace_events(2, 2, "interleaved")
