"""Pipeline memory at GPT-NeoX-20B shapes (BASELINE config 4; reference:
``deepspeed/runtime/pipe/module.py:393`` partitioning + the 1F1B
schedule's activation bound).

Round-2 verdict flagged two unproven design claims; these tests measure
both on the 8-device CPU mesh via XLA ``memory_analysis`` of the real
compiled 1F1B loss+grad program, AOT-lowered from ShapeDtypeStructs (no
20B-scale buffers are ever materialized):

1. **Activation bound**: per-stage temp memory is independent of the
   microbatch count M — the combined fwd+bwd scan's ring buffer really
   is ``peak_in_flight`` slots, not O(M) stashed activations.
2. **Pre/post replication**: the embedding/head replicated over the
   ``pipe`` axis (a deliberate trade — ZeRO shards them over ``data``;
   cond-predicated collectives would be unsafe) costs single-digit
   percent of a stage's block parameters at real NeoX-20B proportions
   (hidden 6144, vocab 50432, 44 layers / 4 stages), so the design
   holds at scale. Numbers recorded in docs/parallelism.md.
"""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec

from hcache_deepspeed_tpu.models.gpt2 import (GPT2Config,
                                              gpt2_pipeline_layers)
from hcache_deepspeed_tpu.parallel import topology as topo_mod
from hcache_deepspeed_tpu.runtime.pipe.module import PipelineModule

NEOX_LAYERS = 44  # real GPT-NeoX-20B depth; compiled depth is scaled


@pytest.fixture
def pipe_topo(eight_devices):
    topo = topo_mod.initialize_topology(
        topo_mod.TopologySpec(pipe=4, data=2))
    yield topo
    topo_mod.reset_topology()


def _compiled_stats(topo, M, n_layer, width, seq, vocab=50432,
                    n_head=16):
    """AOT-compile the 1F1B train program; returns (memory_analysis,
    param shape tree)."""
    cfg = GPT2Config(vocab_size=vocab, n_positions=seq, n_embd=width,
                     n_head=n_head, n_layer=n_layer, dtype="bfloat16",
                     remat=True, use_flash=False, loss_chunk=256)
    layers, loss_fn = gpt2_pipeline_layers(cfg)
    mod = PipelineModule(layers, loss_fn, topology=topo,
                         n_microbatches=M, schedule="1f1b", remat=True)
    rows = M * topo.data_size
    batch_shape = {"input_ids": jax.ShapeDtypeStruct(
        (rows, seq), np.int32,
        sharding=NamedSharding(topo.mesh, PartitionSpec(("data",))))}
    pshape = jax.eval_shape(
        lambda k: mod.init_params(k, {"input_ids": np.zeros((rows, seq),
                                                            np.int32)}),
        jax.random.PRNGKey(0))
    spec_fn = mod.tp_spec_fn()
    flat, treedef = jax.tree_util.tree_flatten_with_path(pshape)
    pspecs = jax.tree_util.tree_unflatten(
        treedef, [spec_fn(p, l) for p, l in flat])
    pargs = jax.tree.map(
        lambda l, s: jax.ShapeDtypeStruct(
            l.shape, l.dtype, sharding=NamedSharding(topo.mesh, s)),
        pshape, pspecs, is_leaf=lambda x: hasattr(x, "shape"))
    rarg = jax.ShapeDtypeStruct(
        (2,), np.uint32,
        sharding=NamedSharding(topo.mesh, PartitionSpec()))

    def step(params, batch, rng):
        return jax.value_and_grad(
            lambda p: mod(p, batch, rng, True))(params)

    compiled = jax.jit(step).lower(pargs, batch_shape, rarg).compile()
    return compiled.memory_analysis(), pshape


def _nbytes(tree):
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))


@pytest.mark.slow
class TestNeoxScalePipelineMemory:

    def test_activation_memory_flat_in_microbatches(self, pipe_topo):
        """1F1B ring buffer: temp bytes must not grow with M."""
        stats = {M: _compiled_stats(pipe_topo, M, n_layer=8, width=1536,
                                    seq=512)[0].temp_size_in_bytes
                 for M in (4, 16)}
        assert stats[16] <= stats[4] * 1.05, (
            f"temp grew with microbatch count: {stats} — the 1F1B "
            "executor is stashing O(M) activations")

    def test_neox_width_compiles_and_replication_is_cheap(self,
                                                          pipe_topo):
        """Real NeoX-20B width/vocab/seq, depth scaled to 8 (2/stage).
        The replicated embedding/head must be a small fraction of a
        stage's block params when extrapolated to the real 44-layer
        depth."""
        ma, pshape = _compiled_stats(pipe_topo, M=8, n_layer=8,
                                     width=6144, seq=2048, n_head=64)
        per_block = _nbytes(pshape["blocks"]) / 8
        replicated = _nbytes(pshape.get("tied", {})) \
            + _nbytes(pshape.get("pre", {})) \
            + _nbytes(pshape.get("post", {}))
        blocks_per_stage_at_scale = \
            per_block * (NEOX_LAYERS / pipe_topo.pipe_size)
        frac = replicated / blocks_per_stage_at_scale
        # measured 2026-08-01: replicated 1.20 GB fp32 vs 18.6 GB/stage
        # blocks at 44 layers -> ~6.5%
        assert frac < 0.15, (
            f"replicated pre/post/tied = {replicated / 1e9:.2f} GB is "
            f"{frac:.1%} of a 44-layer stage's blocks "
            f"({blocks_per_stage_at_scale / 1e9:.2f} GB) — the "
            "replication design does not hold at NeoX scale")
        # and the compiled per-device footprint is finite and sane
        total = ma.argument_size_in_bytes + ma.temp_size_in_bytes \
            + ma.output_size_in_bytes
        assert total < 64 * 1024 ** 3
