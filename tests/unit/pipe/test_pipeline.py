"""Pipeline parallelism tests.

Reference analog: ``tests/unit/runtime/pipe/test_pipe.py`` (trains AlexNet
via PipelineModule at pp=2/4 and compares losses to the non-pipelined
baseline) and ``test_pipe_schedule.py`` (schedule well-formedness).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hcache_deepspeed_tpu.parallel import topology as topo_mod
from hcache_deepspeed_tpu.runtime.pipe import (LayerSpec, PipelineModule,
                                               TrainSchedule, bubble_fraction,
                                               peak_in_flight)
from hcache_deepspeed_tpu.runtime.pipe.schedule import (BackwardPass,
                                                        ForwardPass,
                                                        InferenceSchedule,
                                                        OptimizerStep)


# ------------------------------------------------------------------ #
# Schedules (reference: test_pipe_schedule.py)
# ------------------------------------------------------------------ #
class TestSchedules:

    @pytest.mark.parametrize("micro,stages", [(4, 2), (8, 4), (2, 4), (1, 1)])
    def test_train_schedule_well_formed(self, micro, stages):
        for sid in range(stages):
            sched = TrainSchedule(micro, stages, sid)
            steps = sched.steps()
            fwds = [c.micro_batch_id for step in steps for c in step
                    if type(c) is ForwardPass]
            bwds = [c.micro_batch_id for step in steps for c in step
                    if type(c) is BackwardPass]
            # every microbatch forwarded and backwarded exactly once
            assert sorted(fwds) == list(range(micro))
            assert sorted(bwds) == list(range(micro))
            # bwd i only after fwd i
            flat = [c for step in steps for c in step]
            for mb in range(micro):
                fi = next(i for i, c in enumerate(flat)
                          if type(c) is ForwardPass and c.micro_batch_id == mb)
                bi = next(i for i, c in enumerate(flat)
                          if type(c) is BackwardPass and c.micro_batch_id == mb)
                assert fi < bi
            # 1F1B memory bound: in-flight fwd-not-yet-bwd microbatches
            live = peak = 0
            for c in flat:
                if type(c) is ForwardPass:
                    live += 1
                    peak = max(peak, live)
                elif type(c) is BackwardPass:
                    live -= 1
            assert peak <= peak_in_flight(micro, stages, sid)
            assert type(flat[-1]) is OptimizerStep

    def test_inference_schedule_wavefront(self):
        sched = InferenceSchedule(micro_batches=3, stages=2, stage_id=1)
        steps = sched.steps()
        assert len(steps) == 3 + 2 - 1
        assert steps[0] == []  # stage 1 idle on tick 0 (bubble)

    def test_tick_work_table_matches_schedule(self):
        """The compiled executor's where(stage==0, fresh, carried) logic
        equals the gpipe_tick_work table; the table must agree with the
        InferenceSchedule enumeration."""
        from hcache_deepspeed_tpu.runtime.pipe.schedule import \
            gpipe_tick_work
        M, S = 5, 3
        table = gpipe_tick_work(M, S)
        for sid in range(S):
            steps = InferenceSchedule(M, S, sid).steps()
            for t, cmds in enumerate(steps):
                fwd = [c.micro_batch_id for c in cmds
                       if type(c) is ForwardPass]
                assert table[t][sid] == (fwd[0] if fwd else None)

    def test_bubble(self):
        assert bubble_fraction(8, 4) == pytest.approx(3 / 11)
        assert bubble_fraction(1, 1) == 0.0


# ------------------------------------------------------------------ #
# Compiled executor numerics
# ------------------------------------------------------------------ #
import flax.linen as nn  # noqa: E402


class ToyBlock(nn.Module):
    width: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        return x + nn.Dense(self.width, name="fc")(nn.tanh(x))


def toy_loss(out, batch):
    return jnp.mean((out - batch["target"]) ** 2)


def _toy_module(n_layer, stages, n_micro, topo):
    layers = [LayerSpec(ToyBlock, 8) for _ in range(n_layer)]
    return PipelineModule(layers, toy_loss, topology=topo,
                          num_stages=stages, n_microbatches=n_micro)


class TestPipelinedExecutor:

    def test_matches_sequential(self, eight_devices):
        """Pipelined forward/grads == single-stage sequential execution."""
        topo = topo_mod.initialize_topology(
            topo_mod.TopologySpec(pipe=4, data=2))
        rng = jax.random.PRNGKey(0)
        batch = {
            "input": np.random.RandomState(0).randn(8, 8).astype(np.float32),
            "target": np.random.RandomState(1).randn(8, 8).astype(np.float32),
        }

        # PipelineModule passes `batch` itself to the first layer; ToyBlock
        # expects an array — use a pre layer extracting it
        class Select(nn.Module):
            @nn.compact
            def __call__(self, b, train: bool = False):
                return b["input"]

        layers = [LayerSpec(Select)] + [LayerSpec(ToyBlock, 8)
                                        for _ in range(4)]
        pipe = PipelineModule(layers, toy_loss, topology=topo, num_stages=4,
                              n_microbatches=4)
        seq = PipelineModule(layers, toy_loss, topology=topo, num_stages=1,
                             n_microbatches=4)
        params = pipe.init_params(rng, batch)

        lp, gp = jax.jit(jax.value_and_grad(
            lambda p: pipe(p, batch, None, False)))(params)
        ls, gs = jax.jit(jax.value_and_grad(
            lambda p: seq(p, batch, None, False)))(params)
        assert np.isfinite(float(lp))
        assert float(lp) == pytest.approx(float(ls), rel=1e-5)
        for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gs)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)

    def test_uneven_layers_rejected(self, eight_devices):
        topo = topo_mod.initialize_topology(
            topo_mod.TopologySpec(pipe=4, data=2))
        with pytest.raises(ValueError, match="not divisible"):
            _toy_module(n_layer=6, stages=4, n_micro=4, topo=topo)


# ------------------------------------------------------------------ #
# End-to-end training (reference: test_pipe.py TestPipeCifar10 pattern)
# ------------------------------------------------------------------ #
class TestPipelineEngine:

    def test_gpt2_pipe_trains(self, eight_devices):
        import hcache_deepspeed_tpu as hds
        from hcache_deepspeed_tpu.models.gpt2 import (gpt2_pipeline_layers,
                                                      gpt2_tiny)
        topo = topo_mod.initialize_topology(
            topo_mod.TopologySpec(pipe=2, data=4))
        cfg = gpt2_tiny(n_layer=4)
        layers, loss_fn = gpt2_pipeline_layers(cfg)
        module = PipelineModule(layers, loss_fn, topology=topo)

        config = {
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 4,  # = pipeline microbatches
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1, "min_shard_size": 1},
        }
        rng = np.random.default_rng(0)
        batch = {"input_ids": rng.integers(
            0, cfg.vocab_size, (16, 16), dtype=np.int32)}
        engine, _, _, _ = hds.initialize(model=module, config=config,
                                         example_batch=batch, topology=topo)
        assert engine.is_pipe_parallel
        assert engine.micro_batches == 4
        losses = [float(engine.train_batch(batch=batch)) for _ in range(6)]
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]

    def test_tied_embedding_shared_slot(self, eight_devices):
        from hcache_deepspeed_tpu.models.gpt2 import (gpt2_pipeline_layers,
                                                      gpt2_tiny)
        topo = topo_mod.initialize_topology(
            topo_mod.TopologySpec(pipe=2, data=4))
        cfg = gpt2_tiny(n_layer=2)
        layers, loss_fn = gpt2_pipeline_layers(cfg)
        module = PipelineModule(layers, loss_fn, topology=topo,
                                n_microbatches=2)
        batch = {"input_ids": np.zeros((4, 8), np.int32)}
        params = module.init_params(jax.random.PRNGKey(0), batch)
        # one tied slot holds the single embedding table
        assert "tied" in params and list(params["tied"]) == ["wte"]
        n_embed_tables = sum("weight" in str(k)
                             for k in params["tied"]["wte"])
        assert n_embed_tables == 1
        # partial-manual shard_map must run under jit (the engine always
        # does); eager invocation is unsupported
        loss = jax.jit(module, static_argnums=(3,))(
            params, batch, jax.random.PRNGKey(1), False)
        assert np.isfinite(float(loss))
