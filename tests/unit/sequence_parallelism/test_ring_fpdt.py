"""Ring attention + FPDT long-context tests.

Reference analog: ``tests/unit/sequence_parallelism/test_ulysses.py``
(the reference has no ring/FPDT unit tests — new coverage; numerics are
checked against dense reference attention).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hcache_deepspeed_tpu.ops.flash_attention import reference_attention
from hcache_deepspeed_tpu.parallel import topology as topo_mod
from hcache_deepspeed_tpu.sequence import (HostOffloadKV, chunked_attention,
                                           chunked_lm_loss,
                                           make_ring_attention_fn,
                                           ring_attention)


def _qkv(B=2, T=32, H=4, D=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: rng.standard_normal((B, T, H, D)).astype(np.float32)  # noqa
    return mk(), mk(), mk()


class TestRingAttention:

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, eight_devices, causal):
        topo = topo_mod.initialize_topology(
            topo_mod.TopologySpec(data=2, seq=4))
        q, k, v = _qkv()
        ref = reference_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), causal=causal)
        out = jax.jit(lambda *a: ring_attention(
            *a, causal=causal, topology=topo))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_grads_match_reference(self, eight_devices):
        topo = topo_mod.initialize_topology(
            topo_mod.TopologySpec(data=2, seq=4))
        q, k, v = _qkv(T=16)

        def ring_loss(q, k, v):
            return jnp.sum(ring_attention(q, k, v, causal=True,
                                          topology=topo) ** 2)

        def ref_loss(q, k, v):
            return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

        g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.jit(jax.grad(ref_loss, argnums=(0, 1, 2)))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5)

    def test_llama_trains_with_ring(self, eight_devices):
        import hcache_deepspeed_tpu as hds
        from hcache_deepspeed_tpu.models.llama import (LlamaForCausalLM,
                                                       llama_tiny)
        topo = topo_mod.initialize_topology(
            topo_mod.TopologySpec(data=2, seq=4))
        cfg = llama_tiny(n_kv_head=4)  # ring needs full heads after GQA rep
        model = LlamaForCausalLM(cfg,
                                 attention_fn=make_ring_attention_fn(topo))
        batch = {"input_ids": np.random.default_rng(0).integers(
            0, cfg.vocab_size, (4, 32), dtype=np.int32)}
        engine, _, _, _ = hds.initialize(
            model=model,
            config={"train_micro_batch_size_per_gpu": 2,
                    "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
                    "zero_optimization": {"stage": 1, "min_shard_size": 1}},
            example_batch=batch, topology=topo)
        losses = [float(engine.train_batch(batch=batch)) for _ in range(4)]
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]


class TestChunkedAttention:

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        q, k, v = _qkv(T=64)
        ref = reference_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), causal=causal)
        out = jax.jit(lambda *a: chunked_attention(
            *a, causal=causal, q_chunk=16))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_grads_match(self):
        q, k, v = _qkv(T=32)

        def c_loss(q, k, v):
            return jnp.sum(chunked_attention(q, k, v, q_chunk=8) ** 2)

        def r_loss(q, k, v):
            return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

        gc = jax.jit(jax.grad(c_loss, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.jit(jax.grad(r_loss, argnums=(0, 1, 2)))(q, k, v)
        for a, b in zip(gc, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5)

    def test_bad_chunking_rejected(self):
        q, k, v = _qkv(T=30)
        with pytest.raises(ValueError, match="not divisible"):
            chunked_attention(jnp.asarray(q), jnp.asarray(k),
                              jnp.asarray(v), q_chunk=16)


class TestFpdtAttentionFn:
    """make_fpdt_attention_fn: the chunked kernel behind the model-zoo
    attention hook, composed with Ulysses when the mesh has a seq axis
    (the FPDT composition, reference sequence/fpdt_layer.py)."""

    def test_single_axis_matches_reference(self, eight_devices):
        from hcache_deepspeed_tpu.sequence import make_fpdt_attention_fn
        q, k, v = _qkv(T=32)
        fn = make_fpdt_attention_fn(q_chunk=8)
        assert not fn.supports_gqa
        out = jax.jit(lambda *a: fn(*a, causal=True))(q, k, v)
        ref = reference_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=5e-5)

    def test_engine_trains_with_seq_axis(self, eight_devices):
        import hcache_deepspeed_tpu as hds
        from hcache_deepspeed_tpu.models.llama import (LlamaForCausalLM,
                                                       llama_tiny)
        from hcache_deepspeed_tpu.sequence import make_fpdt_attention_fn
        topo = topo_mod.initialize_topology(
            topo_mod.TopologySpec(data=2, seq=4))
        cfg = llama_tiny(n_kv_head=4)  # hook expands GQA before the kernel
        # no topology kwarg: resolution happens at call time via
        # get_topology(), like the sibling factories
        model = LlamaForCausalLM(
            cfg, attention_fn=make_fpdt_attention_fn(q_chunk=8))
        batch = {"input_ids": np.random.default_rng(0).integers(
            0, cfg.vocab_size, (4, 32), dtype=np.int32)}
        engine, _, _, _ = hds.initialize(
            model=model,
            config={"train_micro_batch_size_per_gpu": 2,
                    "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
                    "zero_optimization": {"stage": 1, "min_shard_size": 1}},
            example_batch=batch, topology=topo)
        losses = [float(engine.train_batch(batch=batch)) for _ in range(4)]
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]


class TestChunkedLoss:

    def test_matches_dense_loss(self):
        from hcache_deepspeed_tpu.models.gpt2 import causal_lm_loss
        rng = np.random.default_rng(0)
        B, T, H, V = 2, 64, 32, 96
        hidden = rng.standard_normal((B, T, H)).astype(np.float32)
        kernel = rng.standard_normal((H, V)).astype(np.float32) * 0.1
        labels = rng.integers(0, V, (B, T)).astype(np.int32)
        labels[0, :5] = -100
        dense = causal_lm_loss(jnp.asarray(hidden) @ kernel,
                               jnp.asarray(labels))
        chunked = jax.jit(lambda h, w, l: chunked_lm_loss(
            h, w, l, chunk=16))(hidden, kernel, labels)
        np.testing.assert_allclose(float(chunked), float(dense), rtol=1e-6)

    def test_grad_flows(self):
        rng = np.random.default_rng(1)
        hidden = rng.standard_normal((1, 32, 16)).astype(np.float32)
        kernel = rng.standard_normal((16, 64)).astype(np.float32)
        labels = rng.integers(0, 64, (1, 32)).astype(np.int32)
        g = jax.jit(jax.grad(
            lambda h: chunked_lm_loss(h, kernel, labels, chunk=8)))(hidden)
        assert np.isfinite(np.asarray(g)).all()
        assert np.abs(np.asarray(g)).max() > 0


class TestHostOffloadKV:

    def test_streamed_matches_reference(self):
        rng = np.random.default_rng(0)
        B, Tq, Tkv, H, D = 1, 8, 64, 4, 16
        q = rng.standard_normal((B, Tq, H, D)).astype(np.float32)
        k = rng.standard_normal((B, Tkv, H, D)).astype(np.float32)
        v = rng.standard_normal((B, Tkv, H, D)).astype(np.float32)
        # q positions at the END of the kv context (decode scoring)
        q_start = Tkv - Tq
        offload = HostOffloadKV(k, v, chunk=16)
        out = offload.attend(jnp.asarray(q), causal=True, q_start=q_start)

        full_q = np.zeros((B, Tkv, H, D), np.float32)
        full_q[:, q_start:] = q
        ref = reference_attention(jnp.asarray(full_q), jnp.asarray(k),
                                  jnp.asarray(v), causal=True)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(ref)[:, q_start:], atol=2e-5)


class TestOffloadedChunkedAttention:
    """Training-capable offloaded FPDT attention (reference:
    fpdt_layer.py:510 _FPDTGPUOffloadingAttentionImpl_)."""

    def _qkv(self, B=2, T=256, H=4, D=32, seed=0):
        rng = np.random.default_rng(seed)
        mk = lambda: jnp.asarray(
            rng.standard_normal((B, T, H, D)), jnp.float32)
        return mk(), mk(), mk()

    def test_matches_plain_chunked(self):
        from hcache_deepspeed_tpu.sequence.fpdt import (
            chunked_attention, offloaded_chunked_attention)
        q, k, v = self._qkv()
        a = chunked_attention(q, k, v, q_chunk=64)
        b = offloaded_chunked_attention(q, k, v, q_chunk=64)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5)

    def test_backward_through_offload_tags(self):
        from hcache_deepspeed_tpu.sequence.fpdt import (
            chunked_attention, offloaded_chunked_attention)
        q, k, v = self._qkv(seed=1)

        def loss_off(q, k, v):
            return offloaded_chunked_attention(
                q, k, v, q_chunk=64).astype(jnp.float32).sum()

        def loss_ref(q, k, v):
            return chunked_attention(
                q, k, v, q_chunk=64).astype(jnp.float32).sum()

        g_off = jax.jit(jax.grad(loss_off, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
        for a, b in zip(g_off, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4)

    def test_offload_policy_compiles_and_matches(self):
        """jax.checkpoint(step, policy=fpdt_offload_policy()): the tagged
        KV residuals route to pinned host memory; numerics unchanged.
        Skipped when the backend has no host memory space."""
        from hcache_deepspeed_tpu.sequence.fpdt import (
            fpdt_offload_policy, offloaded_chunked_attention)
        q, k, v = self._qkv(seed=2)

        def step(q, k, v):
            return offloaded_chunked_attention(
                q, k, v, q_chunk=64).astype(jnp.float32).sum()

        wrapped = jax.checkpoint(step, policy=fpdt_offload_policy())
        try:
            g = jax.jit(jax.grad(wrapped, argnums=(0,)))(q, k, v)[0]
        except Exception as e:  # backend without pinned_host space
            pytest.skip(f"host offload unsupported here: {e}")
        g_ref = jax.jit(jax.grad(step, argnums=(0,)))(q, k, v)[0]
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   atol=1e-4)
