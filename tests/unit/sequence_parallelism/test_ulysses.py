"""Ulysses sequence-parallel tests (reference analog:
tests/unit/sequence_parallelism/test_ulysses.py — all-to-all + attention
equivalence on a simulated multi-rank world; here an 8-device CPU mesh)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from hcache_deepspeed_tpu.ops.flash_attention import reference_attention
from hcache_deepspeed_tpu.parallel import topology as topo_mod
from hcache_deepspeed_tpu.sequence import (DistributedAttention,
                                           seq_all_to_all,
                                           ulysses_attention,
                                           vocab_sequence_parallel_cross_entropy)
from hcache_deepspeed_tpu.sequence.layer import make_ulysses_attention_fn


def _qkv(B=2, T=64, H=8, D=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, T, H, D), jnp.float32) for k in ks)


class TestSeqAllToAll:
    def test_roundtrip(self, eight_devices):
        topo = topo_mod.initialize_topology(topo_mod.TopologySpec(seq=4,
                                                                  data=2))
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 4))

        from jax import shard_map
        mesh = topo.mesh
        spec_in = PartitionSpec(None, "seq", None, None)
        spec_heads = PartitionSpec(None, None, "seq", None)

        fwd = shard_map(
            lambda t: seq_all_to_all(t, "seq", scatter_dim=2, gather_dim=1),
            mesh=mesh, in_specs=spec_in, out_specs=spec_heads)
        bwd = shard_map(
            lambda t: seq_all_to_all(t, "seq", scatter_dim=1, gather_dim=2),
            mesh=mesh, in_specs=spec_heads, out_specs=spec_in)
        y = bwd(fwd(x))
        np.testing.assert_allclose(np.asarray(y), np.asarray(x))


class TestDistributedAttention:
    def test_matches_full_attention(self, eight_devices):
        topo = topo_mod.initialize_topology(topo_mod.TopologySpec(seq=4,
                                                                  data=2))
        q, k, v = _qkv(T=64, H=8)
        ref = reference_attention(q, k, v, causal=True)

        from jax import shard_map
        dist_attn = DistributedAttention(
            functools.partial(reference_attention, causal=True))
        spec = PartitionSpec(None, "seq", None, None)
        out = shard_map(dist_attn, mesh=topo.mesh, in_specs=(spec,) * 3,
                        out_specs=spec)(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


class TestUlyssesSharded:
    def test_matches_full_attention_under_jit(self, eight_devices):
        topo = topo_mod.initialize_topology(topo_mod.TopologySpec(seq=4,
                                                                  data=2))
        q, k, v = _qkv(T=64, H=8)
        ref = reference_attention(q, k, v, causal=True)

        seq_sharding = NamedSharding(topo.mesh,
                                     PartitionSpec(None, "seq", None, None))
        q, k, v = (jax.device_put(x, seq_sharding) for x in (q, k, v))
        fn = jax.jit(functools.partial(ulysses_attention, causal=True,
                                       topology=topo))
        out = fn(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3)

    def test_engine_with_seq_axis(self, eight_devices):
        import hcache_deepspeed_tpu as hds
        from hcache_deepspeed_tpu.models.llama import (LlamaForCausalLM,
                                                       llama_tiny)

        topo = topo_mod.initialize_topology(topo_mod.TopologySpec(seq=4,
                                                                  data=2))
        cfg = llama_tiny(n_head=4, n_kv_head=4)
        attention_fn = make_ulysses_attention_fn(topology=topo)
        model = LlamaForCausalLM(cfg, attention_fn=attention_fn)
        rng = np.random.default_rng(0)
        batch = {"input_ids": rng.integers(0, cfg.vocab_size, (4, 64),
                                           dtype=np.int32)}
        config = {
            "train_batch_size": 4,
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}},
            "zero_optimization": {"stage": 2, "min_shard_size": 1},
        }
        engine, _, _, _ = hds.initialize(model=model, config=config,
                                         example_batch=batch, topology=topo)
        l0 = float(engine.train_batch(batch=batch))
        for _ in range(4):
            l1 = float(engine.train_batch(batch=batch))
        assert np.isfinite(l1) and l1 < l0, (l0, l1)


class TestUlyssesGQA:
    """GQA-compact k/v through the all-to-alls (KV heads divisible by
    sp): H/KV x less kv wire, same math as dense heads."""

    def test_sharded_form_matches_dense(self, eight_devices):
        topo = topo_mod.initialize_topology(topo_mod.TopologySpec(seq=2,
                                                                  data=4))
        rng = np.random.default_rng(0)
        B, T, H, KV, D = 2, 32, 8, 2, 16
        q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, T, KV, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, T, KV, D)), jnp.float32)
        ref = reference_attention(q, k, v, causal=True)

        seq_sharding = NamedSharding(topo.mesh,
                                     PartitionSpec(None, "seq", None, None))
        qs, ks, vs = (jax.device_put(x, seq_sharding) for x in (q, k, v))
        fn = jax.jit(functools.partial(ulysses_attention, causal=True,
                                       topology=topo))
        out = fn(qs, ks, vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3)

    def test_shard_map_form_matches_dense(self, eight_devices):
        topo = topo_mod.initialize_topology(topo_mod.TopologySpec(seq=2,
                                                                  data=4))
        rng = np.random.default_rng(1)
        B, T, H, KV, D = 2, 32, 8, 2, 16
        q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, T, KV, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, T, KV, D)), jnp.float32)
        ref = reference_attention(q, k, v, causal=True)

        from jax import shard_map
        # partial() drops the function attribute — opt in explicitly
        # (reference_attention is GQA-native)
        dist_attn = DistributedAttention(
            functools.partial(reference_attention, causal=True),
            supports_gqa=True)
        assert dist_attn.supports_gqa
        spec = PartitionSpec(None, "seq", None, None)
        out = shard_map(dist_attn, mesh=topo.mesh, in_specs=(spec,) * 3,
                        out_specs=spec)(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_wrapped_plain_kernel_gets_dense_heads(self, eight_devices):
        """A local kernel without GQA support must receive equal head
        counts even when compact k/v go in."""
        topo = topo_mod.initialize_topology(topo_mod.TopologySpec(seq=2,
                                                                  data=4))
        seen = {}

        def plain_kernel(q, k, v, causal=True):
            seen["shapes"] = (q.shape[2], k.shape[2])
            return reference_attention(q, k, v, causal=causal)

        rng = np.random.default_rng(5)
        B, T, H, KV, D = 2, 32, 8, 2, 16
        q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, T, KV, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, T, KV, D)), jnp.float32)
        from jax import shard_map
        dist_attn = DistributedAttention(plain_kernel)
        assert not dist_attn.supports_gqa
        spec = PartitionSpec(None, "seq", None, None)
        out = shard_map(dist_attn, mesh=topo.mesh, in_specs=(spec,) * 3,
                        out_specs=spec)(q, k, v)
        assert seen["shapes"][0] == seen["shapes"][1]   # dense heads
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_indivisible_kv_falls_back_to_expand(self, eight_devices):
        """KV=3 heads, sp=2: expansion path, still correct."""
        topo = topo_mod.initialize_topology(topo_mod.TopologySpec(seq=2,
                                                                  data=4))
        rng = np.random.default_rng(2)
        B, T, H, KV, D = 2, 32, 6, 3, 16
        q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, T, KV, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, T, KV, D)), jnp.float32)
        ref = reference_attention(q, k, v, causal=True)
        seq_sharding = NamedSharding(topo.mesh,
                                     PartitionSpec(None, "seq", None, None))
        qs, ks, vs = (jax.device_put(x, seq_sharding) for x in (q, k, v))
        fn = jax.jit(functools.partial(ulysses_attention, causal=True,
                                       topology=topo))
        out = fn(qs, ks, vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3)

    def test_gqa_llama_trains_with_ulysses(self, eight_devices):
        import hcache_deepspeed_tpu as hds
        from hcache_deepspeed_tpu.models.llama import (LlamaForCausalLM,
                                                       llama_tiny)
        topo = topo_mod.initialize_topology(topo_mod.TopologySpec(seq=2,
                                                                  data=4))
        cfg = llama_tiny(n_head=4, n_kv_head=2)   # GQA, KV % sp == 0
        attention_fn = make_ulysses_attention_fn(topology=topo)
        assert attention_fn.supports_gqa
        model = LlamaForCausalLM(cfg, attention_fn=attention_fn)
        rng = np.random.default_rng(3)
        batch = {"input_ids": rng.integers(0, cfg.vocab_size, (8, 64),
                                           dtype=np.int32)}
        engine, _, _, _ = hds.initialize(
            model=model, example_batch=batch, topology=topo,
            config={"train_batch_size": 8,
                    "train_micro_batch_size_per_gpu": 2,
                    "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}},
                    "zero_optimization": {"stage": 2,
                                          "min_shard_size": 1}})
        l0 = float(engine.train_batch(batch=batch))
        for _ in range(4):
            l1 = float(engine.train_batch(batch=batch))
        assert np.isfinite(l1) and l1 < l0


class TestUnevenHeads:
    """H % sp != 0 (reference: deepspeed/sequence/layer.py:111 uneven
    head distribution): pad-and-mask keeps shapes static; outputs must
    match dense attention exactly where it counts — the real heads."""

    def test_sharded_form_h6_sp4(self, eight_devices):
        topo = topo_mod.initialize_topology(topo_mod.TopologySpec(seq=4,
                                                                  data=2))
        q, k, v = _qkv(T=64, H=6)
        ref = reference_attention(q, k, v, causal=True)
        seq_sharding = NamedSharding(topo.mesh,
                                     PartitionSpec(None, "seq", None, None))
        qs, ks, vs = (jax.device_put(x, seq_sharding) for x in (q, k, v))
        fn = jax.jit(functools.partial(ulysses_attention, causal=True,
                                       topology=topo))
        out = fn(qs, ks, vs)
        assert out.shape == q.shape
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3)

    def test_shard_map_form_h6_sp4(self, eight_devices):
        topo = topo_mod.initialize_topology(topo_mod.TopologySpec(seq=4,
                                                                  data=2))
        q, k, v = _qkv(T=64, H=6, seed=3)
        ref = reference_attention(q, k, v, causal=True)
        from jax import shard_map
        dist_attn = DistributedAttention(
            functools.partial(reference_attention, causal=True))
        spec = PartitionSpec(None, "seq", None, None)
        out = shard_map(dist_attn, mesh=topo.mesh, in_specs=(spec,) * 3,
                        out_specs=spec)(q, k, v)
        assert out.shape == q.shape
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_uneven_gqa_forces_dense_then_pads(self, eight_devices):
        """H=6, KV=3, sp=4: compact kv can't split over sp either —
        expand + pad, still exact."""
        topo = topo_mod.initialize_topology(topo_mod.TopologySpec(seq=4,
                                                                  data=2))
        rng = np.random.default_rng(7)
        B, T, H, KV, D = 2, 32, 6, 3, 16
        q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, T, KV, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, T, KV, D)), jnp.float32)
        ref = reference_attention(q, k, v, causal=True)
        from jax import shard_map
        dist_attn = DistributedAttention(
            functools.partial(reference_attention, causal=True),
            supports_gqa=True)
        spec = PartitionSpec(None, "seq", None, None)
        out = shard_map(dist_attn, mesh=topo.mesh, in_specs=(spec,) * 3,
                        out_specs=spec)(q, k, v)
        assert out.shape == q.shape
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_llama_trains_with_uneven_heads(self, eight_devices):
        import hcache_deepspeed_tpu as hds
        from hcache_deepspeed_tpu.models.llama import (LlamaForCausalLM,
                                                       llama_tiny)
        topo = topo_mod.initialize_topology(topo_mod.TopologySpec(seq=4,
                                                                  data=2))
        cfg = llama_tiny(hidden_size=96, intermediate_size=192,
                         n_head=6, n_kv_head=6)   # 6 heads, sp=4
        attention_fn = make_ulysses_attention_fn(topology=topo)
        model = LlamaForCausalLM(cfg, attention_fn=attention_fn)
        rng = np.random.default_rng(11)
        batch = {"input_ids": rng.integers(0, cfg.vocab_size, (4, 64),
                                           dtype=np.int32)}
        engine, _, _, _ = hds.initialize(
            model=model, example_batch=batch, topology=topo,
            config={"train_batch_size": 4,
                    "train_micro_batch_size_per_gpu": 2,
                    "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}},
                    "zero_optimization": {"stage": 2,
                                          "min_shard_size": 1}})
        l0 = float(engine.train_batch(batch=batch))
        for _ in range(4):
            l1 = float(engine.train_batch(batch=batch))
        assert np.isfinite(l1) and l1 < l0


class TestSPCrossEntropy:
    def test_matches_dense(self, eight_devices):
        topo = topo_mod.initialize_topology(topo_mod.TopologySpec(seq=8))
        B, T, V = 2, 16, 64
        logits = jax.random.normal(jax.random.PRNGKey(0), (B, T, V))
        labels = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, V)
        labels = labels.at[0, :3].set(-100)

        # dense reference
        logp = jax.nn.log_softmax(logits, axis=-1)
        valid = labels != -100
        nll = -jnp.take_along_axis(
            logp, jnp.where(valid, labels, 0)[..., None], -1).squeeze(-1)
        ref = (jnp.where(valid, nll, 0).sum() /
               jnp.maximum(valid.sum(), 1))

        from jax import shard_map
        out = shard_map(
            lambda lg, lb: vocab_sequence_parallel_cross_entropy(lg, lb),
            mesh=topo.mesh,
            in_specs=(PartitionSpec(None, None, "seq"),
                      PartitionSpec(None, None)),
            out_specs=PartitionSpec())(logits, labels)
        np.testing.assert_allclose(float(out), float(ref), rtol=1e-5)
