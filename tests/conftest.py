"""Test harness.

Reference analog: ``tests/unit/common.py`` — there, multi-process
``torch.multiprocessing`` + file-store rendezvous simulates a cluster; here
the TPU-native analog is a *virtual 8-device CPU mesh* via
``--xla_force_host_platform_device_count`` (SURVEY.md §4): every sharding,
collective and ZeRO path executes exactly as it would across chips, inside
one process.

These env vars must be set before JAX initialises its backends, which is why
they live at conftest import time.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")
os.environ.setdefault("HDS_LOG_LEVEL", "warning")

import jax  # noqa: E402
import pytest  # noqa: E402

# jax may have been preloaded at interpreter startup (before this conftest
# ran), in which case the env vars above were read too late; force the
# platform through the live config instead. Backends are still lazy at
# collection time, so this takes effect.
jax.config.update("jax_platforms", "cpu")

# HARNESS RULE — one collective launch in flight at a time.
#
# XLA's CPU in-process collectives make every participating device thread
# block in a rendezvous (rendezvous.cc). Device programs run on a *shared*
# thread pool, so if a Python loop enqueues many launches without
# synchronizing, pool threads end up parked in different launches'
# rendezvous and the process dies with SIGABRT after the 40s termination
# timeout — taking all of pytest down (empirically deterministic on a
# 1-core host with 8 virtual devices; `jax_cpu_enable_async_dispatch=False`
# does NOT cover sharded computations and does not help).
#
# Any test loop that repeatedly calls a jitted function containing
# psum/all_gather/etc must therefore `jax.block_until_ready(...)` (or fetch
# a scalar) every iteration — which is also what the real engine train loop
# does by fetching the loss.


@pytest.fixture(autouse=True)
def _reset_singletons():
    yield
    from hcache_deepspeed_tpu.parallel import topology
    topology.reset_topology()


@pytest.fixture
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs


# ------------------------------------------------------------------ #
# Test tiers (reference: tests/pytest.ini marker discipline).
#
# The 8-virtual-device engine compiles dominate suite wall clock
# (30-90 s per distinct engine/mesh program on this host), so every
# module that builds engines or lowers full train programs is
# auto-marked `slow`. The smoke tier
#
#     python -m pytest tests/ -m "not slow" -q        (< 5 min)
#
# keeps per-component unit coverage (schedule math, packing, config
# parsing, masks, importers, launcher command builders, kernels at
# tiny shapes) plus one true engine smoke (test_smoke_engine.py); the
# full suite is the nightly bar:
#
#     python -m pytest tests/ -q
# ------------------------------------------------------------------ #
_SLOW_PATH_PARTS = (
    "runtime/test_engine.py",
    "runtime/test_compression.py",
    "runtime/test_structured_compression.py",
    "runtime/test_multislice.py",
    "runtime/test_mics.py",
    "runtime/test_zeropp.py",
    "runtime/test_zeropp_layered.py",
    "runtime/test_offload.py",
    "runtime/test_hybrid_engine.py",
    "runtime/test_domino_hlo.py",
    "runtime/test_infinity.py",
    "runtime/test_data_pipeline.py",
    "runtime/test_sparse_domino_elastic.py",
    "runtime/test_indexed_dataset.py",
    "runtime/test_comm_dtype.py",
    "tests/unit/pipe/",
    "tests/unit/moe/",
    "tests/unit/sequence_parallelism/",
    "tests/unit/inference/",
    "tests/unit/models/",
    "checkpoint/test_universal.py",
    "checkpoint/test_moe_checkpoint.py",
    "tests/unit/test_bench_configs.py",
    "tests/unit/test_aux_subsystems.py",
    "tests/unit/test_auto_tp.py",
    "tests/integration/",
)


def pytest_collection_modifyitems(config, items):
    for item in items:
        path = str(item.fspath).replace("\\", "/")
        if any(part in path for part in _SLOW_PATH_PARTS):
            item.add_marker(pytest.mark.slow)
