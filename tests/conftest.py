"""Test harness.

Reference analog: ``tests/unit/common.py`` — there, multi-process
``torch.multiprocessing`` + file-store rendezvous simulates a cluster; here
the TPU-native analog is a *virtual 8-device CPU mesh* via
``--xla_force_host_platform_device_count`` (SURVEY.md §4): every sharding,
collective and ZeRO path executes exactly as it would across chips, inside
one process.

These env vars must be set before JAX initialises its backends, which is why
they live at conftest import time.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")
os.environ.setdefault("HDS_LOG_LEVEL", "warning")

import jax  # noqa: E402
import pytest  # noqa: E402

# jax may have been preloaded at interpreter startup (before this conftest
# ran), in which case the env vars above were read too late; force the
# platform through the live config instead. Backends are still lazy at
# collection time, so this takes effect.
jax.config.update("jax_platforms", "cpu")

# HARNESS RULE — one collective launch in flight at a time.
#
# XLA's CPU in-process collectives make every participating device thread
# block in a rendezvous (rendezvous.cc). Device programs run on a *shared*
# thread pool, so if a Python loop enqueues many launches without
# synchronizing, pool threads end up parked in different launches'
# rendezvous and the process dies with SIGABRT after the 40s termination
# timeout — taking all of pytest down (empirically deterministic on a
# 1-core host with 8 virtual devices; `jax_cpu_enable_async_dispatch=False`
# does NOT cover sharded computations and does not help).
#
# Any test loop that repeatedly calls a jitted function containing
# psum/all_gather/etc must therefore `jax.block_until_ready(...)` (or fetch
# a scalar) every iteration — which is also what the real engine train loop
# does by fetching the loss.


@pytest.fixture(autouse=True)
def _reset_singletons():
    yield
    from hcache_deepspeed_tpu.parallel import topology
    topology.reset_topology()


@pytest.fixture
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs
