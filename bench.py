"""Benchmark: prints ONE JSON line {metric, value, unit, vs_baseline}.

GPT-2 350M causal-LM training throughput on one chip (BASELINE config 1
family scaled up to a size whose MFU is meaningful on a v5e chip — at
125M the vocab head and HBM traffic dominate and no framework reaches
the Ulysses bar), bf16 params + fp32 Adam, fused train step, Pallas
flash attention. ``vs_baseline`` reports measured MFU divided by the
reference's published 54% MFU (Ulysses blog headline, BASELINE.md) —
the portable efficiency yardstick when the hardware differs from the
reference's A100/H100 runs.

Round-2 measured points on the v5e chip (see memory/axon-env-and-bench):
this config ran at 49.9% MFU; batch>=16 or 760M variants crash the
remote compile helper, so the largest reliable point ships.
"""

import json
import os
import sys
import threading
import time

import numpy as np

# Wall-clock watchdog: through the axon tunnel a dead relay makes the
# first JAX call hang forever at backend init. A clean JSON error line
# beats an infinite hang for whoever is recording this run.
_WATCHDOG_SECS = float(os.environ.get("HDS_BENCH_WATCHDOG_SECS", 900))
_DONE = threading.Event()   # set before the success print: a timer that
# fires in the completion window must not add a second JSON line


def _metric_label():
    return ("gpt2-tiny SMOKE tokens/sec (not a benchmark)"
            if os.environ.get("HDS_BENCH_TINY") == "1" else
            "gpt2-350m train tokens/sec/chip (bf16, seq1024)")


def _arm_watchdog():
    def fire():
        if _DONE.is_set():
            return
        print(json.dumps({
            "metric": _metric_label(),
            "value": 0.0,
            "unit": "tokens/sec",
            "vs_baseline": 0.0,
            "error": f"watchdog: no result within {_WATCHDOG_SECS:.0f}s "
                     "(TPU relay unreachable?)",
        }), flush=True)
        os._exit(2)

    t = threading.Timer(_WATCHDOG_SECS, fire)
    t.daemon = True
    t.start()
    return t


def main():
    watchdog = _arm_watchdog()
    import jax

    import hcache_deepspeed_tpu as hds
    from hcache_deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from hcache_deepspeed_tpu.platform import get_platform

    if os.environ.get("HDS_BENCH_TINY") == "1":
        # smoke config: exercises the identical code path in seconds on
        # a CPU backend (numbers are meaningless there)
        batch, seq = 2, 128
        mcfg = GPT2Config(n_layer=2, n_embd=64, n_head=4, n_positions=seq,
                          vocab_size=256, dtype="bfloat16", remat=False)
    else:
        batch, seq = 8, 1024
        mcfg = GPT2Config(n_layer=24, n_embd=1024, n_head=16,
                          n_positions=seq, vocab_size=50257,
                          dtype="bfloat16", remat=False)
    model = GPT2LMHeadModel(mcfg)
    rng = np.random.default_rng(0)
    data = {"input_ids": rng.integers(
        0, mcfg.vocab_size, (batch, seq), dtype=np.int32)}

    cfg = {
        "train_batch_size": batch,
        "train_micro_batch_size_per_gpu": batch,
        "optimizer": {"type": "AdamW",
                      "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "gradient_clipping": 1.0,
        "zero_optimization": {"stage": 0},
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = hds.initialize(model=model, config=cfg,
                                     example_batch=data)

    # warmup / compile (sync via host fetch of the loss scalar — through a
    # tunnelled PJRT backend block_until_ready alone may not drain the queue)
    for _ in range(3):
        loss = float(engine.train_batch(batch=data))

    # Steps chain through engine.state on device, so enqueueing them all and
    # fetching one scalar at the end costs a single host round-trip; fetching
    # per step would add the tunnel RTT (tens of ms) to every step.
    steps = 30
    t0 = time.perf_counter()
    for _ in range(steps):
        loss_dev = engine.train_batch(batch=data)
    loss = float(loss_dev)
    dt = time.perf_counter() - t0

    tokens_per_sec = steps * batch * seq / dt
    n_params = sum(x.size for x in jax.tree.leaves(engine.state["params"]))
    # 6N (fwd+bwd) weight FLOPs + 12*L*S*d attention FLOPs per token
    flops_per_token = 6 * n_params + 12 * mcfg.n_layer * seq * mcfg.n_embd
    achieved_tflops = tokens_per_sec * flops_per_token / 1e12
    peak = get_platform().peak_tflops("bfloat16")
    mfu = achieved_tflops / peak if peak else 0.0
    vs_baseline = (mfu / 0.54) if peak else 0.0

    _DONE.set()
    watchdog.cancel()
    print(json.dumps({
        "metric": _metric_label(),
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(vs_baseline, 4),
        "extra": {
            "mfu": round(mfu, 4),
            "achieved_tflops": round(achieved_tflops, 2),
            "peak_tflops": peak,
            "loss": float(loss),
            "n_params": int(n_params),
            "step_time_ms": round(dt / steps * 1000, 2),
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
