"""Benchmark: prints ONE JSON line {metric, value, unit, vs_baseline}.

GPT-2 350M causal-LM training throughput on one chip (BASELINE config 1
family scaled up to a size whose MFU is meaningful on a v5e chip — at
125M the vocab head and HBM traffic dominate and no framework reaches
the Ulysses bar), bf16 params + fp32 Adam, fused train step, Pallas
flash attention. ``vs_baseline`` reports measured MFU divided by the
reference's published 54% MFU (Ulysses blog headline, BASELINE.md) —
the portable efficiency yardstick when the hardware differs from the
reference's A100/H100 runs.

Candidate-runner structure: the axon relay's *remote compile* service
is a separate failure domain from program *execution* — when it wedges,
already-compiled programs still run but any new shape hangs forever at
compile. So the parent process runs each candidate config in a child
process with a hard timeout (a hung compile sits in a C call and can
only be killed from outside), measures every candidate that fits in the
wall-clock budget, and reports the best by MFU. The list ends with the
config known to be server-side compile-cached, so a wedged compile
service still produces a real number; a candidate timing out (the wedge
signature) skips straight to that cached config. Child mode is selected
with ``HDS_BENCH_CHILD=<config name>``.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np

# Wall-clock watchdog: through the axon tunnel a dead relay makes the
# first JAX call hang forever at backend init. A clean JSON error line
# beats an infinite hang for whoever is recording this run.
_WATCHDOG_SECS = float(os.environ.get("HDS_BENCH_WATCHDOG_SECS", 1800))
# per-candidate budget (compile + 30 measured steps; a healthy relay
# compiles this program in ~60-90s)
_CAND_SECS = float(os.environ.get("HDS_BENCH_CAND_SECS", 420))
# floor reserved for the final cache-proven candidate (it completes in
# ~200s when the relay executes at all)
_LAST_RESERVE = 300.0
_DONE = threading.Event()   # set before the success print: a timer that
# fires in the completion window must not add a second JSON line
_CHILD = None               # current candidate subprocess, for the watchdog

# Ordered best-first; the LAST entry must be the config known to be in
# the relay's server-side compile cache (it is what previous rounds ran),
# so it still reports when the remote-compile service is wedged.
# All are GPT-2 350M-class (n_params within 1% of each other); the
# model-shape deltas are TPU layout fixes, not model shrinkage:
#   hd128  — 8 heads x head_dim 128: full-depth MXU contractions in the
#            flash kernel (at head_dim 64 the systolic array runs half
#            empty during QK^T / AV)
#   vpad   — vocab 50304 (128-multiple): lane-aligned LM-head matmul
#   lchunk — chunked LM loss: no [B, T, V] fp32 logits materialization
# 350m-hd128-b8 measured best (62.66% MFU, 2026-08-01) — first, so a
# budget-truncated run still measures the winner; lchunk variant second
# (59.76%); the cache-proven fallback stays last (workflow contract)
CANDIDATES = ["350m-hd128-b8", "350m-hd128-lchunk-b8", "350m-b8"]

# Configs beyond CANDIDATES stay reachable for manual measurement via
# HDS_BENCH_CHILD=<name> (how new candidates get vetted on the chip
# before joining the list).
CONFIGS = {
    # the round-2 measured point: 50.3% MFU, server-cache-proven
    "350m-b8": dict(batch=8, n_head=16, vocab_size=50257, loss_chunk=0),
    "350m-hd128-b8": dict(batch=8, n_head=8, vocab_size=50304,
                          loss_chunk=0),
    "350m-hd128-lchunk-b8": dict(batch=8, n_head=8, vocab_size=50304,
                                 loss_chunk=256),
    "350m-hd128-lchunk-b16": dict(batch=16, n_head=8, vocab_size=50304,
                                  loss_chunk=256),
    "350m-hd128-lchunk-b32": dict(batch=32, n_head=8, vocab_size=50304,
                                  loss_chunk=256),
    # flash-kernel tiling variants of the winner (vet on chip)
    "350m-hd128-lchunk-b8-blk256x256": dict(batch=8, n_head=8,
                                        vocab_size=50304, loss_chunk=256,
                                        block_q=256, block_k=256),
    "350m-hd128-lchunk-b8-blk512x1024": dict(batch=8, n_head=8,
                                          vocab_size=50304,
                                          loss_chunk=256, block_q=512,
                                          block_k=1024),
    # long-context points (FPDT/Ulysses story: BASELINE row 2's 55% MFU
    # bar), remat on; tokens/step = batch*seq (8k and 16k — NOT equal,
    # compare MFU, not tokens/sec)
    "350m-hd128-lchunk-seq4k-b2": dict(batch=2, seq=4096, n_head=8,
                                       vocab_size=50304, loss_chunk=256,
                                       remat=True),
    "350m-hd128-lchunk-seq16k-b1": dict(batch=1, seq=16384, n_head=8,
                                        vocab_size=50304, loss_chunk=256,
                                        remat=True),
    # remat-policy variants: plain remat=True recomputes every matmul in
    # backward (~8N FLOPs/token vs 6N), capping measured MFU near 75% of
    # hardware util. dots_saveable keeps matmul outputs (bf16 residuals)
    # and recomputes only elementwise — the memory must fit, hence vet.
    "350m-hd128-lchunk-seq4k-b2-rpdots": dict(
        batch=2, seq=4096, n_head=8, vocab_size=50304, loss_chunk=256,
        remat=True, remat_policy="dots_saveable"),
    "350m-hd128-lchunk-seq16k-b1-rpdots": dict(
        batch=1, seq=16384, n_head=8, vocab_size=50304, loss_chunk=256,
        remat=True, remat_policy="dots_saveable"),
    "7b-layer-seq2k-b2-rpdots": dict(model="llama", batch=2, seq=2048,
                                     hidden=4096, ffn=11008, n_head=32,
                                     n_layer=2, vocab_size=4096,
                                     loss_chunk=256, remat=True,
                                     remat_policy="dots_saveable"),
    "7b-layer-seq4k-b1-rpdots": dict(model="llama", batch=1, seq=4096,
                                     hidden=4096, ffn=11008, n_head=32,
                                     n_layer=2, vocab_size=4096,
                                     loss_chunk=256, remat=True,
                                     remat_policy="dots_saveable"),
    "350m-hd128-b16": dict(batch=16, n_head=8, vocab_size=50304,
                           loss_chunk=0),
    "350m-vpad-b8": dict(batch=8, n_head=16, vocab_size=50304,
                         loss_chunk=0),
    # Llama-7B layer microbench (BASELINE north star = ZeRO-3
    # Llama-2-7B): real 7B block shapes (h=4096, ffn=11008, 32 heads x
    # head_dim 128) at 2 layers + tiny vocab, the closest single-chip
    # proxy for per-layer training MFU + HBM headroom at 7B widths.
    # fp32 master+Adam for 2 blocks ~ 4.9 GB + bf16 params + remat'd
    # activations fits a 16 GB chip; vet via HDS_BENCH_CHILD.
    "7b-layer-seq2k-b2": dict(model="llama", batch=2, seq=2048,
                              hidden=4096, ffn=11008, n_head=32,
                              n_layer=2, vocab_size=4096,
                              loss_chunk=256, remat=True),
    "7b-layer-seq4k-b1": dict(model="llama", batch=1, seq=4096,
                              hidden=4096, ffn=11008, n_head=32,
                              n_layer=2, vocab_size=4096,
                              loss_chunk=256, remat=True),
    # never in CANDIDATES: a seconds-cheap config for exercising the
    # measured (non-tiny) path off-chip, e.g. the CPU-fallback guard
    "tiny-cpu-guard": dict(batch=2, seq=128, n_layer=2, n_embd=64,
                           n_head=4, vocab_size=256, loss_chunk=0,
                           record=False),
}


def build_model(name):
    """(model, model_config, batch, seq) for one CONFIGS entry. Shared
    with tests/unit/test_bench_configs.py so the pre-vetting trace test
    builds exactly the model the bench measures (a private copy there
    drifted once: it hardcoded n_layer=24 and missed tiny-cpu-guard's
    2-layer shape)."""
    spec = CONFIGS[name]
    if spec.get("model") == "llama":
        from hcache_deepspeed_tpu.models.llama import (LlamaConfig,
                                                       LlamaForCausalLM)
        batch, seq = spec["batch"], spec["seq"]
        mcfg = LlamaConfig(vocab_size=spec["vocab_size"],
                           hidden_size=spec["hidden"],
                           intermediate_size=spec["ffn"],
                           n_layer=spec["n_layer"],
                           n_head=spec["n_head"],
                           n_kv_head=spec["n_head"],
                           max_positions=seq, dtype="bfloat16",
                           remat=spec.get("remat", False),
                           remat_policy=spec.get("remat_policy", ""),
                           loss_chunk=spec["loss_chunk"],
                           flash_block_q=spec.get("block_q", 0),
                           flash_block_k=spec.get("block_k", 0))
        return LlamaForCausalLM(mcfg), mcfg, batch, seq
    from hcache_deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    batch, seq = spec["batch"], spec.get("seq", 1024)
    mcfg = GPT2Config(n_layer=spec.get("n_layer", 24),
                      n_embd=spec.get("n_embd", 1024),
                      n_head=spec["n_head"],
                      n_positions=seq, vocab_size=spec["vocab_size"],
                      dtype="bfloat16", remat=spec.get("remat", False),
                      remat_policy=spec.get("remat_policy", ""),
                      loss_chunk=spec["loss_chunk"],
                      flash_block_q=spec.get("block_q", 0),
                      flash_block_k=spec.get("block_k", 0))
    return GPT2LMHeadModel(mcfg), mcfg, batch, seq


def _metric_label():
    return ("gpt2-tiny SMOKE tokens/sec (not a benchmark)"
            if os.environ.get("HDS_BENCH_TINY") == "1" else
            "gpt2-350m train tokens/sec/chip (bf16, seq1024)")


# Every successful chip measurement is persisted here; error paths report
# it as ``extra.last_measured`` so a round captured while the relay is
# dead still transmits the last real number (distinguishing "never fast"
# from "fast but unreachable" for whoever reads the artifact). The file
# IS committed on purpose — a fresh clone must carry the last round's
# measured {best,last} as its dead-relay fallback.
_LAST_MEASURED_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".bench_last_measured.json")


def _record_last_measured(entry):
    """Persist ``last`` (most recent chip measurement) and ``best``
    (highest-MFU ever), so vetting runs of experimental configs can't
    erase the winner's number from the dead-relay report."""
    state = _load_last_measured() or {}
    state["last"] = entry
    best = state.get("best")
    if best is None or entry.get("mfu", 0.0) >= best.get("mfu", 0.0):
        state["best"] = entry
    try:
        tmp = _LAST_MEASURED_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, _LAST_MEASURED_PATH)
    except OSError as e:   # never fail the measurement, but say so
        print(f"[bench] could not persist last_measured: {e}",
              file=sys.stderr)


def _load_last_measured():
    try:
        with open(_LAST_MEASURED_PATH) as f:
            state = json.load(f)
        return state if isinstance(state, dict) else None
    except (OSError, json.JSONDecodeError):
        return None


def _error_payload(message):
    """Dead-relay payload. The driver's scoreboard records ``value``
    verbatim and any consumer may key on the exit code, so a round with
    ZERO fresh measurement must never masquerade as a successful
    best-ever result (that masks regressions introduced since the last
    real run). ``value`` therefore stays 0.0 with the error string
    saying why, and the historical best/last chip numbers ride along
    only under ``extra.last_measured`` — with a top-level
    ``"stale": true`` marker when such history exists — for readers who
    want to distinguish "never fast" from "fast but unreachable"."""
    payload = {
        "metric": _metric_label(),
        "value": 0.0,
        "unit": "tokens/sec",
        "vs_baseline": 0.0,
        "error": message,
    }
    # smoke artifacts must not carry real chip numbers
    state = (None if os.environ.get("HDS_BENCH_TINY") == "1"
             else _load_last_measured())
    if state is not None:
        best = state.get("best") or state.get("last")
        if best and best.get("value"):
            payload["stale"] = True
            payload["stale_utc"] = best.get("utc", "")
        payload["extra"] = {"last_measured": state}
        # surface the wedge age as a number (queryable gauge via the
        # perf registry — ROADMAP item 5's condition stops being a
        # log-archaeology exercise)
        age = _staleness_days(payload.get("stale_utc", ""))
        if age is not None:
            payload["extra"]["staleness_days"] = round(age, 2)
    return payload


def _staleness_days(stale_utc):
    """Age in days of a ``%Y-%m-%dT%H:%M:%SZ`` timestamp (None when
    absent/unparseable)."""
    if not stale_utc:
        return None
    try:
        then = time.mktime(time.strptime(
            stale_utc, "%Y-%m-%dT%H:%M:%SZ")) - time.timezone
    except ValueError:
        return None
    return max(0.0, (time.time() - then) / 86400.0)


def _error_exit_code(payload):
    """No-fresh-measurement exit codes, both non-zero so exit-code
    consumers can never mistake a dead-relay round for a real run:
    3 = stale history available under extra.last_measured, 2 = nothing
    at all."""
    return 3 if payload.get("stale") else 2


def _arm_watchdog():
    def fire():
        if _DONE.is_set():
            return
        if _CHILD is not None:
            try:
                _CHILD.kill()   # don't orphan a child wedged on the relay
            except Exception:
                pass
        payload = _error_payload(
            f"watchdog: no result within {_WATCHDOG_SECS:.0f}s "
            "(TPU relay unreachable?)")
        print(json.dumps(payload), flush=True)
        os._exit(_error_exit_code(payload))

    t = threading.Timer(_WATCHDOG_SECS, fire)
    t.daemon = True
    t.start()
    return t


def run_config(name):
    """Measure one candidate; prints the result JSON line."""
    import jax

    tiny = os.environ.get("HDS_BENCH_TINY") == "1"
    if tiny:
        # The smoke config must never touch the TPU relay: the axon
        # plugin initialises alongside cpu even under JAX_PLATFORMS=cpu
        # (its register() runs from sitecustomize), and a wedged relay
        # then hangs backend init. Forcing the platform through the live
        # config (the conftest trick) keeps the smoke path host-only.
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    import hcache_deepspeed_tpu as hds
    from hcache_deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from hcache_deepspeed_tpu.platform import get_platform

    if not tiny and get_platform().name == "cpu":
        # CPU fallback (mis-set env / relay plugin failing fast): refuse
        # BEFORE the 33-step measurement — a 350M config takes minutes
        # per step on CPU and would be misdiagnosed as a wedged compile
        # service by the child timeout. A real TPU whose device_kind has
        # no peak-TFLOPs entry is NOT refused (tokens/sec is still real;
        # mfu just reads 0).
        print(json.dumps(_error_payload(
            "backend is 'cpu', not TPU; refusing to measure/record a "
            "CPU-measured result as a chip metric")), flush=True)
        _DONE.set()
        return

    if tiny:
        # smoke config: exercises the identical code path in seconds on
        # a CPU backend (numbers are meaningless there)
        batch, seq = 2, 128
        mcfg = GPT2Config(n_layer=2, n_embd=64, n_head=4, n_positions=seq,
                          vocab_size=256, dtype="bfloat16", remat=False)
        model = GPT2LMHeadModel(mcfg)
    else:
        model, mcfg, batch, seq = build_model(name)
    rng = np.random.default_rng(0)
    # clamp below every config's vocab so the sampled batch is identical
    # across padded-vocab variants
    data = {"input_ids": rng.integers(
        0, min(mcfg.vocab_size, 50257), (batch, seq), dtype=np.int32)}

    cfg = {
        "train_batch_size": batch,
        "train_micro_batch_size_per_gpu": batch,
        "optimizer": {"type": "AdamW",
                      "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "gradient_clipping": 1.0,
        "zero_optimization": {"stage": 0},
        "steps_per_print": 10 ** 9,
    }
    if not tiny:
        # persistent local compilation cache: the relay's REMOTE compile
        # service wedges independently of execution (the round-4 failure
        # mode); a locally cached executable skips it entirely, so a
        # config measured once stays measurable across wedges/restarts.
        # If the axon PJRT client can't serialize executables, JAX logs
        # a warning and runs uncached — strictly no worse.
        cfg["compile"] = {"cache_dir": hds.default_compile_cache_dir()}
    engine, _, _, _ = hds.initialize(model=model, config=cfg,
                                     example_batch=data)

    # warmup / compile (sync via host fetch of the loss scalar — through a
    # tunnelled PJRT backend block_until_ready alone may not drain the queue)
    for _ in range(3):
        loss = float(engine.train_batch(batch=data))

    # Steps chain through engine.state on device, so enqueueing them all and
    # fetching one scalar at the end costs a single host round-trip; fetching
    # per step would add the tunnel RTT (tens of ms) to every step.
    # The measured window runs under the span tracer so the JSONL
    # artifact carries a per-step breakdown — the next regression is
    # attributable from the artifact alone (host-issue spans here; the
    # device truth needs an XLA profile).
    from hcache_deepspeed_tpu.telemetry import bench_extra
    from hcache_deepspeed_tpu.telemetry.tracer import get_tracer
    tracer = get_tracer()
    tracer_was = tracer.enabled
    tracer.configure(enabled=True)
    tracer.clear()
    steps = 30
    t0 = time.perf_counter()
    for _ in range(steps):
        loss_dev = engine.train_batch(batch=data)
    loss = float(loss_dev)
    dt = time.perf_counter() - t0
    tracer.configure(enabled=tracer_was)
    step_breakdown = bench_extra(tracer.events())

    tokens_per_sec = steps * batch * seq / dt
    n_params = sum(x.size for x in jax.tree.leaves(engine.state["params"]))
    # 6N (fwd+bwd) weight FLOPs + 12*L*S*d attention FLOPs per token
    width = getattr(mcfg, "n_embd", 0) or mcfg.hidden_size
    flops_per_token = 6 * n_params + 12 * mcfg.n_layer * seq * width
    achieved_tflops = tokens_per_sec * flops_per_token / 1e12
    peak = get_platform().peak_tflops("bfloat16")
    mfu = achieved_tflops / peak if peak else 0.0
    vs_baseline = (mfu / 0.54) if peak else 0.0

    _DONE.set()
    # configs marked record=False (dev-only shapes like tiny-cpu-guard)
    # must never overwrite the committed chip 'last' record
    if not tiny and CONFIGS.get(name, {}).get("record", True):
        _record_last_measured({
            "value": round(tokens_per_sec, 1),
            "mfu": round(mfu, 4),
            "vs_baseline": round(vs_baseline, 4),
            "config": name,
            "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        })
    print(json.dumps({
        "metric": _metric_label(),
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(vs_baseline, 4),
        "extra": {
            "config": "tiny" if tiny else name,
            "seq": seq,
            "mfu": round(mfu, 4),
            "achieved_tflops": round(achieved_tflops, 2),
            "peak_tflops": peak,
            "loss": float(loss),
            "n_params": int(n_params),
            "step_time_ms": round(dt / steps * 1000, 2),
            "step_breakdown": step_breakdown,
            # reference-path fallback counters: a perf number measured
            # while the quantized matmul silently ran the
            # dequantize-then-matmul path describes the wrong kernel
            "qmm_fallbacks": _qmm_fallback_row(),
        },
    }), flush=True)


def _qmm_fallback_row():
    """JSON-safe snapshot of the quantized-matmul reference-path
    fallback counters, also emitted as a telemetry instant so the
    record lands in the trace stream, not just a one-shot warning."""
    from hcache_deepspeed_tpu.ops.quantized_matmul import \
        fallback_debug_info
    from hcache_deepspeed_tpu.telemetry.tracer import get_tracer
    info = fallback_debug_info()
    row = {"count": info["count"], "by_reason": info["by_reason"],
           "last": list(info["last"]) if info["last"] else None}
    tracer = get_tracer()
    if tracer.enabled and info["count"]:
        tracer.instant("qmm.fallback", count=info["count"],
                       reasons=",".join(sorted(info["by_reason"])))
    return row


_PROBE_SECS = float(os.environ.get("HDS_BENCH_PROBE_SECS", 150))


def _probe_relay():
    """~2-minute relay health check BEFORE burning candidate budget.

    A fresh random shape forces a REMOTE compile, so this detects the
    round-4 wedge (compile service dead, execution alive) as well as a
    fully dead relay. Round 4 spent 29 min of candidate timeouts to
    learn what this learns in <=150 s.

    Returns ``"up"``, ``"timeout"`` (hang — the wedge signature; cached
    programs may still execute) or ``"no-tpu"`` (fast failure — no TPU
    backend at all, e.g. CPU-fallback box; nothing TPU-side will run).

    The shape space must be large enough that repeated probes (this one
    plus bin/relay_probe.sh every ~4 min for hours) cannot populate the
    relay's server-side compile cache and turn a wedged service into a
    false "up" — two random dims from [131, 2048) give ~3.7M shapes.
    """
    code = (
        "import jax, jax.numpy as jnp, random\n"
        "m, n = random.randrange(131, 2048), random.randrange(131, 2048)\n"
        "assert jax.devices('tpu')\n"
        "x = jnp.ones((m, n))\n"
        "float(jax.jit(lambda a: (a @ a.T).sum())(x))\n"
    )
    try:
        rc = subprocess.run([sys.executable, "-c", code],
                            timeout=_PROBE_SECS,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL).returncode
    except subprocess.TimeoutExpired:
        return "timeout"
    return "up" if rc == 0 else "no-tpu"


def _run_candidate_subprocess(name, timeout):
    """Run one candidate in a child (a hung remote compile can only be
    SIGKILLed from outside); returns (parsed result dict | None, timed_out)."""
    global _CHILD
    env = dict(os.environ, HDS_BENCH_CHILD=name)
    proc = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, env=env, text=True)
    _CHILD = proc
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        print(f"[bench] candidate {name}: no result in {timeout:.0f}s "
              "(remote compile wedged?)", file=sys.stderr)
        return None, True
    finally:
        _CHILD = None
    for line in reversed(out.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "value" in parsed and "error" not in parsed:
                return parsed, False
    print(f"[bench] candidate {name}: exited rc={proc.returncode} "
          f"without a result line; last output:\n"
          + "\n".join(out.splitlines()[-5:]), file=sys.stderr)
    return None, False


def run_zero_overlap(out_path=None):
    """``--zero-overlap``: CPU-deterministic audit of the explicit
    ZeRO-3 comm/compute overlap pipeline (docs/zero_overlap.md).

    Builds the 2-layer toy ZeRO-3 (qwZ) step on an 8-virtual-device
    CPU mesh, audits the compiled HLO with ``profiling/hlo_audit.py``
    for prefetch on vs ``overlap_comm=False``, checks bitwise parity
    between the two schedules over 3 steps, repeats both audits on the
    QUANTIZED-WIRE config (bucketed int8 reduce-scatter + error
    feedback + fused qwZ matmul consumption) with wire-bytes-saved per
    collective op recorded from the comms logger AND the compiled
    module, audits the decomposed flat-ring AND hierarchical (2-D mesh,
    ``comm/hierarchical.py``) transports — bitwise parity vs native,
    per-mesh-axis wire bytes, inter-axis quantized fraction, and
    modeled pod-scale wire seconds from the declared per-axis
    bandwidths — re-runs the Domino half-batch all-reduce audit
    (full-width + int8-wire + decomposed + hierarchical) through the
    explicit async-issue helper, and emits one JSONL row per
    measurement plus a summary line. Runs entirely on
    CPU — never touches the TPU relay — so the artifact is reproducible
    anywhere (native async pairs are expected to be 0 here; the derived
    tier is the CPU-decidable evidence).

    Chip-truth mode (``HDS_ZERO_OVERLAP_PLATFORM=tpu``, driven by
    ``bin/chip_overlap_campaign.sh`` behind the relay probe): the same
    phases run on real TPU devices and land in ``ZERO_OVERLAP_TPU.jsonl``
    — there the NATIVE tier is the verdict: either the scheduler
    finally emits async pairs for the monolithic collectives, or the
    decomposed permute chains carry the overlap structurally (ROADMAP
    item 5's either-outcome resolution)."""
    platform = os.environ.get("HDS_ZERO_OVERLAP_PLATFORM", "cpu")
    if out_path is None:
        out_path = "ZERO_OVERLAP.jsonl" if platform == "cpu" \
            else "ZERO_OVERLAP_TPU.jsonl"
    if platform == "cpu":
        # must run before jax initializes its backends
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")
    import jax
    if platform == "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    elif len(jax.devices()) < 8:
        print(json.dumps(_error_payload(
            f"zero-overlap tpu mode: need >= 8 devices, found "
            f"{len(jax.devices())}")), flush=True)
        _DONE.set()
        return 3
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    import hcache_deepspeed_tpu as hds
    from hcache_deepspeed_tpu.comm.comms_logging import get_comms_logger
    from hcache_deepspeed_tpu.models.gpt2 import GPT2LMHeadModel, gpt2_tiny
    from hcache_deepspeed_tpu.profiling.hlo_audit import audit_compiled
    from hcache_deepspeed_tpu.telemetry.tracer import get_tracer

    tracer = get_tracer()
    tracer.configure(enabled=True)
    comms = get_comms_logger()
    comms.configure(enabled=True)

    rng = np.random.default_rng(0)
    data = {"input_ids": rng.integers(0, 256, (8, 32), dtype=np.int32)}

    def build(overlap, **zero_extra):
        model = GPT2LMHeadModel(gpt2_tiny(
            n_layer=2, n_embd=64, n_head=4, use_flash=False))
        zero = {"stage": 3, "min_shard_size": 1,
                "zero_quantized_weights": True,
                "overlap_comm": overlap}
        zero.update(zero_extra)
        cfg = {
            "train_batch_size": 8,
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": zero,
            "comms_logger": {"enabled": True},
            "steps_per_print": 10 ** 9,
        }
        engine, _, _, _ = hds.initialize(model=model, config=cfg,
                                         example_batch=data)
        return engine

    rows, losses, params = [], {}, {}
    for overlap in (True, False):
        comms.reset()
        engine = build(overlap)
        report, row = engine.zero_overlap_report(data)
        losses[overlap] = [float(engine.train_batch(batch=data))
                           for _ in range(3)]
        params[overlap] = jax.tree.leaves(engine.state["params"])
        row.update({
            "phase": "zero3-audit", "overlap_comm": overlap,
            "comm_bytes": {op: {ax: tot for ax, (_, tot) in by.items()}
                           for op, by in comms.axis_summary().items()
                           if op.startswith(("zero_", "qwZ", "qgZ",
                                             "domino", "issue."))},
            "wire_savings": comms.wire_savings_summary(),
        })
        rows.append(row)

    bitwise = (losses[True] == losses[False] and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(params[True], params[False])))

    # ---- quantized wire: bucketed int8 reduce-scatter + error
    # feedback + fused qwZ matmul consumption, prefetch on. Gates:
    # wire <= ~35% of the fp32 full-width bytes, loss trajectory
    # within tolerance of the full-width run, depth-1-vs-0 bitwise
    # parity preserved UNDER quantization.
    q_losses, q_params = {}, {}
    qrs_row = None
    for overlap in (True, False):
        comms.reset()
        engine = build(overlap,
                       zero_quantized_reduce_scatter=True,
                       zero_reduce_scatter_error_feedback=True,
                       zero_quantized_weights_fused_matmul=True)
        report, row = engine.zero_overlap_report(data)
        q_losses[overlap] = [float(engine.train_batch(batch=data))
                             for _ in range(3)]
        q_params[overlap] = jax.tree.leaves(engine.state["params"])
        row.update({
            "phase": "zero3-audit-quantized-wire",
            "overlap_comm": overlap,
            "alltoall_overlap_ratio": round(
                report.overlap_ratio("all-to-all"), 4),
            "wire_savings": comms.wire_savings_summary(),
        })
        if overlap:
            qrs_row = row
        rows.append(row)
    q_bitwise = (q_losses[True] == q_losses[False] and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(q_params[True], q_params[False])))
    qrs_frac = qrs_row["wire_savings"].get(
        "zero_qrs_all_to_all", {}).get("fraction")
    traj_ok = bool(np.allclose(q_losses[True], losses[True], rtol=5e-2))
    rows.append({
        "phase": "quantized-wire-parity", "steps": 3,
        "bitwise_depth_parity": q_bitwise,
        "losses": q_losses[True],
        "fp_wire_losses": losses[True],
        "trajectory_within_tol": traj_ok,
        "qrs_wire_fraction_of_fp32": qrs_frac,
        "qmm_fallbacks": _qmm_fallback_row(),
    })
    on = next(r for r in rows if r["overlap_comm"])
    off = next(r for r in rows if not r["overlap_comm"])
    on_pairs = [p for p in on["pairs"]
                if p["kind"].startswith("all-gather")
                and p["interleaved"] >= 1]
    off_pairs = [p for p in off["pairs"]
                 if p["kind"].startswith("all-gather")
                 and p["interleaved"] >= 1]
    rows.append({"phase": "parity", "steps": 3, "bitwise": bitwise,
                 "losses": losses[True]})

    # ---- decomposed ring collectives (zero_collective_impl=
    # decomposed): the gather/reduce lanes ride chunked-ppermute
    # chains (comm/ring.py) so overlap is STRUCTURAL — scored by the
    # auditor's structural_overlap_ratio over collective-permute ops,
    # gated >= the native derived ratio for BOTH lanes, and
    # bitwise-equal to the native transport at depth 1 and 0.
    d_losses, d_params, d_rows = {}, {}, {}
    for prefetch in (True, False):
        comms.reset()
        extra = {"zero_collective_impl": "decomposed"}
        if not prefetch:
            extra["stage3_prefetch_bucket_size"] = 0
        engine = build(True, **extra)
        report, row = engine.zero_overlap_report(data)
        d_losses[prefetch] = [float(engine.train_batch(batch=data))
                              for _ in range(3)]
        d_params[prefetch] = jax.tree.leaves(engine.state["params"])
        row.update({
            "phase": "zero3-audit-decomposed", "prefetch": prefetch,
            "ring_permute_bytes": comms.permute_bytes_summary(),
            "wire_savings": comms.wire_savings_summary(),
        })
        d_rows[prefetch] = row
        rows.append(row)
    dec_bitwise = (
        d_losses[True] == d_losses[False] == losses[True]
        and all(np.array_equal(np.asarray(x), np.asarray(y))
                and np.array_equal(np.asarray(x), np.asarray(z))
                for x, y, z in zip(params[True], d_params[True],
                                   d_params[False])))
    structural = d_rows[True]["structural_overlap_ratio"]
    dec_chain_max = max(
        (c["length"] for c in d_rows[True]["permute_chains"]),
        default=0)

    # quantized wire over the ring transport: per-ring-chunk
    # quantization preserves EF residuals + bucket layout, so the
    # decomposed qwire run is bitwise-equal to the native qwire run
    comms.reset()
    engine = build(True, zero_collective_impl="decomposed",
                   zero_quantized_reduce_scatter=True,
                   zero_reduce_scatter_error_feedback=True,
                   zero_quantized_weights_fused_matmul=True)
    report, row = engine.zero_overlap_report(data)
    dq_losses = [float(engine.train_batch(batch=data)) for _ in range(3)]
    dq_params = jax.tree.leaves(engine.state["params"])
    dq_bitwise = (dq_losses == q_losses[True] and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(q_params[True], dq_params)))
    row.update({
        "phase": "zero3-audit-decomposed-qwire", "prefetch": True,
        "ring_permute_bytes": comms.permute_bytes_summary(),
        "wire_savings": comms.wire_savings_summary(),
    })
    dq_structural = row["structural_overlap_ratio"]
    rows.append(row)
    rows.append({
        "phase": "decomposed-parity", "steps": 3,
        "bitwise_vs_native": dec_bitwise,
        "bitwise_qwire_vs_native_qwire": dq_bitwise,
        "losses": d_losses[True],
        "structural_overlap_ratio": structural,
        "structural_ge_native_gather": bool(
            structural >= on["gather_overlap_ratio"]),
        "structural_ge_native_reduce": bool(
            structural >= on["reduce_overlap_ratio"]),
        "max_permute_chain_len": dec_chain_max,
    })

    # ---- hierarchical (2-D mesh) collectives, zero_collective_impl=
    # hierarchical: the flat data axis declared as a 2x4 mesh
    # (outer/long-haul "inter" axis of 2, fast "intra" axis of 4), the
    # gather/reduce lanes riding per-axis grouped ring phases
    # (comm/hierarchical.py). Gates: bitwise parity vs the native AND
    # flat-ring transports (plain + quantized wire), inter-axis wire
    # bytes of the quantized run <= 0.35x the all-full-width
    # hierarchical run, structural overlap >= the flat rings on at
    # least one lane, and modeled pod-scale wire seconds per axis.
    HIER = {"zero_collective_impl": "hierarchical",
            "zero_mesh_shape": [2, 4]}
    #: declared wire-cost model inputs (NOT measurements): the pod
    #: projection target (configurable via ``--pod-shape RxC``;
    #: default the v5e-256 as a 16x16 mesh), fast axis at ICI-class
    #: 45 GB/s per device, long-haul axis priced at DCN-class
    #: 6.75 GB/s — the EQuARX bandwidth asymmetry the axis-selective
    #: quantization spends its bits against
    HIER_TOY_SIZES = {"inter": 2, "intra": 4}
    pod_arg = "16x16"
    argv = sys.argv[1:]
    if "--pod-shape" in argv:
        pod_arg = argv[argv.index("--pod-shape") + 1]
    try:
        pod_inter, pod_intra = (int(t) for t in
                                pod_arg.lower().split("x"))
    except ValueError:
        print(json.dumps(_error_payload(
            f"--pod-shape {pod_arg!r}: expected RxC (e.g. 16x16)")),
            flush=True)
        _DONE.set()
        return 3
    HIER_POD_SIZES = {"inter": pod_inter, "intra": pod_intra}
    HIER_GBPS = {"inter": 6.75, "intra": 45.0}

    def hier_run(phase, **extra):
        comms.reset()
        engine = build(True, **extra)
        report, row = engine.zero_overlap_report(data)
        losses = [float(engine.train_batch(batch=data))
                  for _ in range(3)]
        params = jax.tree.leaves(engine.state["params"])
        row.update({
            "phase": phase, "prefetch": True,
            "ring_permute_bytes": comms.permute_bytes_summary(),
            "ring_permute_axis_bytes": comms.permute_axis_bytes(),
            "axis_bytes": comms.total_axis_bytes(),
            "wire_savings": comms.wire_savings_summary(),
        })
        rows.append(row)
        return row, losses, params

    h_row, h_losses, h_params = hier_run("zero3-audit-hierarchical",
                                         **HIER)
    hier_bitwise_native = (h_losses == losses[True] and all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(h_params, params[True])))
    hier_bitwise_flat = (h_losses == d_losses[True] and all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(h_params, d_params[True])))

    # all-full-width hierarchical (qwZ off) — the inter-axis byte
    # DENOMINATOR, plus a full-width flat-ring twin for bitwise parity
    comms.reset()
    engine = build(True, zero_quantized_weights=False,
                   zero_collective_impl="decomposed")
    fwd_losses = [float(engine.train_batch(batch=data))
                  for _ in range(3)]
    fwd_params = jax.tree.leaves(engine.state["params"])
    fw_row, fw_losses, fw_params = hier_run(
        "zero3-audit-hierarchical-fullwidth",
        zero_quantized_weights=False, **HIER)
    hier_fw_bitwise_flat = (fw_losses == fwd_losses and all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(fw_params, fwd_params)))

    # quantized wire over the hierarchical transport (qwZ gather +
    # bucketed int8 reduce-scatter + EF + fused matmul consumption):
    # every long-haul byte rides int8 — the inter-axis NUMERATOR
    hq_row, hq_losses, hq_params = hier_run(
        "zero3-audit-hierarchical-qwire",
        zero_quantized_reduce_scatter=True,
        zero_reduce_scatter_error_feedback=True,
        zero_quantized_weights_fused_matmul=True, **HIER)
    hier_qwire_bitwise = (hq_losses == q_losses[True] and all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(hq_params, q_params[True])))

    # axis-selective long-haul quantization of the fp gather lane
    # (zero_longhaul_wire_bits): full width intra, int8 inter — values
    # change only for long-haul rows, gated on trajectory tolerance
    # like every lossy wire, plus the matched-pair wire fraction
    lh_row, lh_losses, _ = hier_run(
        "zero3-audit-hierarchical-longhaul",
        zero_quantized_weights=False, zero_longhaul_wire_bits=8, **HIER)
    lh_frac = lh_row["wire_savings"].get(
        "zero_hier_all_gather_longhaul", {}).get("fraction")
    lh_traj_ok = bool(np.allclose(lh_losses, fw_losses, rtol=5e-2))

    fw_inter = fw_row["axis_bytes"].get("inter", 0)
    hq_inter = hq_row["axis_bytes"].get("inter", 0)
    hier_interaxis_fraction = round(hq_inter / fw_inter, 4) \
        if fw_inter else None
    hier_structural = max(h_row["structural_overlap_ratio"],
                          hq_row["structural_overlap_ratio"])

    # modeled wire seconds: measured per-axis bytes of the quantized
    # run priced at the declared toy bandwidths, and projected to the
    # declared 16x16 pod mesh (assumption recorded in the row)
    from hcache_deepspeed_tpu.profiling.hlo_audit import (
        pod_scale_wire_seconds, wire_cost_seconds)
    hier_cost_toy = wire_cost_seconds(hq_row["axis_bytes"], HIER_GBPS)
    hier_cost_pod = pod_scale_wire_seconds(
        hq_row["axis_bytes"], HIER_TOY_SIZES, HIER_POD_SIZES, HIER_GBPS)
    fw_cost_pod = pod_scale_wire_seconds(
        fw_row["axis_bytes"], HIER_TOY_SIZES, HIER_POD_SIZES, HIER_GBPS)
    rows.append({
        "phase": "hierarchical-parity", "steps": 3,
        "mesh_spec": h_row.get("mesh_spec"),
        "bitwise_vs_native": hier_bitwise_native,
        "bitwise_vs_flat": hier_bitwise_flat,
        "fullwidth_bitwise_vs_flat": hier_fw_bitwise_flat,
        "qwire_bitwise_vs_native_qwire": hier_qwire_bitwise,
        "losses": h_losses,
        "structural_overlap_ratio": hier_structural,
        "structural_ge_flat": bool(hier_structural >= structural),
        "interaxis_wire_bytes_quantized": hq_inter,
        "interaxis_wire_bytes_fullwidth": fw_inter,
        "interaxis_wire_fraction": hier_interaxis_fraction,
        "longhaul_gather_wire_fraction": lh_frac,
        "longhaul_trajectory_within_tol": lh_traj_ok,
        "wire_cost_toy": hier_cost_toy,
        "wire_cost_pod_quantized": hier_cost_pod,
        "wire_cost_pod_fullwidth": fw_cost_pod,
        "pod_axis_sizes": HIER_POD_SIZES,
        "pod_shape": pod_arg,
        "link_gbytes_per_s": HIER_GBPS,
    })

    # ---- unified hpZ tiering on the mesh (ISSUE 15 tentpole):
    # zero_hpz_partition_size=4 maps onto the 2x4 mesh's intra axis —
    # per-micro gathers ride the fast tier's grouped rings, the
    # secondary refresh rides the full mesh. Gates: the transport swap
    # (hier-hpz vs native-hpz, everything else fixed) is BITWISE at
    # full width AND under qwZ, and the secondary refresh's bytes are
    # attributed per mesh axis (zero_hier_secondary) instead of
    # staying a native blind spot.
    comms.reset()
    engine = build(True, zero_quantized_weights=False,
                   zero_hpz_partition_size=4)
    nfwhpz_losses = [float(engine.train_batch(batch=data))
                     for _ in range(3)]
    nfwhpz_params = jax.tree.leaves(engine.state["params"])
    hz_row, hz_losses, hz_params = hier_run(
        "zero3-audit-hier-hpz-unified", zero_quantized_weights=False,
        zero_hpz_partition_size=4, **HIER)
    hpz_fw_bitwise = (hz_losses == nfwhpz_losses and all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(hz_params, nfwhpz_params)))
    comms.reset()
    engine = build(True, zero_hpz_partition_size=4)
    nqhpz_losses = [float(engine.train_batch(batch=data))
                    for _ in range(3)]
    nqhpz_params = jax.tree.leaves(engine.state["params"])
    hzq_row, hzq_losses, hzq_params = hier_run(
        "zero3-audit-hier-hpz-qw", zero_hpz_partition_size=4, **HIER)
    hpz_qw_bitwise = (hzq_losses == nqhpz_losses and all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(hzq_params, nqhpz_params)))
    hpz_secondary_axes = hz_row["ring_permute_axis_bytes"].get(
        "zero_hier_secondary", {})
    hpz_secondary_on_mesh = bool(
        hpz_secondary_axes.get("intra") and
        hpz_secondary_axes.get("inter"))
    hpz_unified_bitwise = bool(hpz_fw_bitwise and hpz_qw_bitwise)
    rows.append({
        "phase": "hier-hpz-unified-parity", "steps": 3,
        "hpz": 4, "hpz_tiers": [{"axis": "intra", "span": 4}],
        "bitwise_fullwidth_vs_native_hpz": hpz_fw_bitwise,
        "bitwise_qw_vs_native_hpz": hpz_qw_bitwise,
        "unified_hpz_bitwise": hpz_unified_bitwise,
        "secondary_refresh_on_mesh": hpz_secondary_on_mesh,
        "secondary_refresh_axis_bytes": hpz_secondary_axes,
        "losses": hz_losses,
    })

    # ---- phase-pipelined hierarchical collectives (ISSUE 15
    # tentpole): zero_mesh_pipeline_chunks=2 splits every gather/
    # exchange payload into column chunks riding independent full
    # phase chains — chunk k's long-haul phase structurally
    # independent of chunk k+1's intra phase, scored by the auditor's
    # NEW cross-axis permute-pair tier. Gates: bitwise vs the
    # unpipelined hierarchical engine, structural overlap >= the PR 12
    # number, primitive-level cross-axis pairs >= 1 pipelined and == 0
    # unpipelined.
    hp_row, hp_losses, hp_params = hier_run(
        "zero3-audit-hier-pipelined", zero_mesh_pipeline_chunks=2,
        **HIER)
    pipelined_bitwise = (hp_losses == h_losses and all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(hp_params, h_params)))
    pipelined_structural = hp_row["structural_overlap_ratio"]
    # primitive cross-axis audit: the pipelined gather's long-haul
    # phase really is dependence-free of the next chunk's intra phase
    from hcache_deepspeed_tpu.comm.hierarchical import (
        hierarchical_all_gather, make_mesh_spec)
    prim_spec = make_mesh_spec([2, 4])
    prim_mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("d",))
    prim_x = jnp.ones((8, 64), jnp.float32)
    prim_cross = {}
    for pc in (1, 2):
        def prim(xl, pc=pc):
            return hierarchical_all_gather(
                xl[0], "d", prim_spec, pipeline_chunks=pc)[None]
        compiled = jax.jit(jax.shard_map(
            prim, mesh=prim_mesh, in_specs=(P("d"),),
            out_specs=P("d"), check_vma=False)).lower(prim_x).compile()
        prim_cross[pc] = audit_compiled(compiled).cross_axis
    rows.append({
        "phase": "hier-pipelined-parity", "steps": 3,
        "pipeline_chunks": 2,
        "bitwise_vs_unpipelined": pipelined_bitwise,
        "structural_overlap_ratio": pipelined_structural,
        "structural_ge_flat": bool(pipelined_structural >= structural),
        "engine_cross_axis_pairs": hp_row["cross_axis_pairs"],
        "primitive_cross_axis_unpipelined": prim_cross[1],
        "primitive_cross_axis_pipelined": prim_cross[2],
        "losses": hp_losses,
    })
    pipelined_cross_ok = (prim_cross[1]["pairs"] == 0
                          and prim_cross[2]["pairs"] >= 1)

    # ---- 16-device factorings (ISSUE 15): 4x4 and 2x8 parity in a
    # 16-virtual-device child interpreter (the same program the slow
    # test runs), so the grouped-ring machinery is proven past the
    # 8-device toy matrix in the committed artifact itself.
    from hcache_deepspeed_tpu.comm.benchmark import run_16dev_parity
    try:
        facts16 = run_16dev_parity(
            repo_root=os.path.dirname(os.path.abspath(__file__)))
        hier_16dev_parity = bool(facts16["parity"])
    except Exception as exc:  # noqa: BLE001 — recorded, gates fail
        facts16 = {"error": repr(exc)}
        hier_16dev_parity = False
    rows.append(dict(facts16, phase="hier-16dev",
                     parity=hier_16dev_parity))

    # ---- measured wire calibration (ISSUE 15): time per-axis grouped
    # ppermute rounds (wall clock — the one deliberately impure leg)
    # and re-price the pod projection with MEASURED bandwidths; the
    # declared-vs-measured divergence rides in the row. On CPU the
    # numbers are physically meaningless — the shape/contract is the
    # gate here; on chip this leg IS the calibration
    # (bin/chip_overlap_campaign.sh).
    from hcache_deepspeed_tpu.comm.benchmark import calibrate_mesh_axes
    cal_spec = make_mesh_spec(
        [2, 4], link_gbytes_per_s=[HIER_GBPS["inter"],
                                   HIER_GBPS["intra"]])
    cal = calibrate_mesh_axes(cal_spec, mesh=prim_mesh, axis="d",
                              payload_bytes=(1 << 14, 1 << 18),
                              trials=3)
    cal_pod = pod_scale_wire_seconds(
        hq_row["axis_bytes"], HIER_TOY_SIZES, HIER_POD_SIZES,
        cal["gbytes_per_s"], calibration="measured")
    wire_cal_shape_ok = bool(
        set(cal["gbytes_per_s"]) == {"inter", "intra"}
        and all(np.isfinite(v) and v > 0
                for v in cal["gbytes_per_s"].values())
        and all(r["seconds_per_round"] > 0 for r in cal["rows"])
        and cal_pod["calibration"] == "measured")
    rows.append({
        "phase": "wire-calibration",
        "calibration": cal["calibration"],
        "backend": cal["backend"],
        "measured_gbytes_per_s": cal["gbytes_per_s"],
        "declared_gbytes_per_s": HIER_GBPS,
        "divergence_vs_declared": cal["divergence_vs_declared"],
        "per_payload_rows": cal["rows"],
        "wire_cost_pod_measured": cal_pod,
        "pod_shape": pod_arg,
        "shape_ok": wire_cal_shape_ok,
    })

    # ---- fused computation-collective kernels (ISSUE 18 tentpole):
    # zero_collective_impl=fused rides the hierarchical transport
    # twins for bucket payloads and consumes qwZ matmul leaves
    # MID-GATHER (ops/fused_collective_matmul.py — on CPU the bitwise
    # reference twin; the streamed/Pallas schedules carry the audit
    # and wall-clock evidence). Gates: engine bitwise vs native on the
    # plain AND quantized wire, the auditor's in-kernel tier scoring
    # >= 1 subsumed permute+dot pair where the unfused program scores
    # 0, fused <= unfused wall clock at the largest rig payload, 3-D
    # mesh bookkeeping at the 16x16 pod factoring, and the 16-device
    # fused parity legs.
    FUSED = {"zero_collective_impl": "fused", "zero_mesh_shape": [2, 4],
             "zero_mesh_axis_roles": ["data", "data"]}

    # (a) plain wire: fused transports are the hierarchical twins —
    # bitwise vs the native AND hierarchical engines
    f_row, f_losses, f_params = hier_run("zero3-audit-fused", **FUSED)
    f_fused_bytes = comms.fused_bytes_summary()
    f_row["fused_permute_bytes"] = f_fused_bytes
    fused_parity_plain = (f_losses == losses[True] and all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(f_params, params[True])))
    fused_bitwise_hier = (f_losses == h_losses and all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(f_params, h_params)))

    # (b) quantized wire + mid-gather consumption: qwZ mm-leaves ship
    # as raw (int8, scales) shard pairs and fold through the fused
    # gather-matmul at the Dense; the cotangent bucket folds through
    # the fused quant-EF + qrs-exchange epilogue — still bitwise vs
    # the native quantized-wire engine
    fq_row, fq_losses, fq_params = hier_run(
        "zero3-audit-fused-qwire",
        zero_quantized_reduce_scatter=True,
        zero_reduce_scatter_error_feedback=True,
        zero_quantized_weights_fused_matmul=True, **FUSED)
    fq_fused_bytes = comms.fused_bytes_summary()
    fq_row["fused_permute_bytes"] = fq_fused_bytes
    fused_parity_qwire = (fq_losses == q_losses[True] and all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(fq_params, q_params[True])))
    fused_mid_gather_leaves = fq_row.get("mid_gather_leaves", 0)
    rows.append({
        "phase": "fused-parity", "steps": 3,
        "bitwise_vs_native": fused_parity_plain,
        "bitwise_vs_hierarchical": fused_bitwise_hier,
        "qwire_bitwise_vs_native_qwire": fused_parity_qwire,
        "mid_gather_leaves": fused_mid_gather_leaves,
        "losses": f_losses,
        "fused_permute_bytes_qwire": fq_fused_bytes,
    })

    # (c) in-kernel audit tier: the STREAMED fused schedule (per ring
    # step, the next chunk's permute beside the resident chunk's
    # dequant-dot) compiled next to the unfused gather-then-matmul —
    # the fused module must score scoped subsumed pairs, the unfused
    # module must score zero
    from hcache_deepspeed_tpu.ops.fused_collective_matmul import (
        streamed_fused_gather_matmul)
    from hcache_deepspeed_tpu.ops.quantized_matmul import (
        quantize_for_matmul, quantized_matmul)
    fa_mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("d",))
    fwq, fws = quantize_for_matmul(
        jnp.asarray(rng.normal(size=(128, 64)), jnp.float32), 8)
    fx = jnp.asarray(rng.normal(size=(16, 128)), jnp.float32)

    def fgm_stream(xl, ql, sl):
        return streamed_fused_gather_matmul(xl, ql, sl, group_k=8,
                                            shard_dim=0, axis_name="d")

    def fgm_unfused(xl, ql, sl):
        qa = jax.lax.all_gather(ql, "d")
        sa = jax.lax.all_gather(sl, "d")
        return quantized_matmul(xl, qa.reshape(-1, 64),
                                sa.reshape(-1, 64), group_k=8)

    def _fused_audit(f):
        return audit_compiled(jax.jit(jax.shard_map(
            f, mesh=fa_mesh, in_specs=(P(), P("d"), P("d")),
            out_specs=P(), check_vma=False)).lower(fx, fwq,
                                                   fws).compile())

    aud_fused = _fused_audit(fgm_stream)
    aud_unfused = _fused_audit(fgm_unfused)
    fused_subsumed = aud_fused.fused_kernel["subsumed_pairs"]
    unfused_subsumed = aud_unfused.fused_kernel["subsumed_pairs"]
    fused_audit_gate = bool(fused_subsumed >= 1
                            and unfused_subsumed == 0)
    farow = aud_fused.to_row()
    farow.update({
        "phase": "fused-audit", "variant": "streamed",
        "fused_kernel": dict(aud_fused.fused_kernel),
        "unfused_subsumed_pairs": unfused_subsumed,
        "unfused_fused_wire_bytes":
            aud_unfused.fused_kernel["wire_bytes"],
        "audit_gate": fused_audit_gate,
    })
    rows.append(farow)

    # (d) wall-clock rig: streamed fused vs the native unfused
    # pipeline per payload (best-of-trials), with the qmm/fused
    # fallback counters snapshot riding in the row — on CPU the
    # counters record the deliberate reference dispatch
    from hcache_deepspeed_tpu.comm.benchmark import fused_vs_unfused_bench
    fb = fused_vs_unfused_bench(mesh=fa_mesh, axis="d", trials=3)
    fb_largest = max(fb["rows"], key=lambda r: r["k"] * r["n"])
    fused_wallclock_speedup = fb_largest["speedup"]
    fused_le_unfused_largest = fb["fused_le_unfused_largest"]
    rows.append(dict(fb, phase="fused-bench",
                     largest_payload=fb_largest))

    # (e) 3-D mesh composition: declared non-ZeRO axis roles — the
    # fused ring rides the data sub-box of a (data, model, pipe)
    # factoring; host-side bookkeeping gates at the 16x16 pod
    # factoring and a composed 3-D spec (rank/coord round-trips,
    # axis-group partitions, role sub-factoring)
    from hcache_deepspeed_tpu.comm.hierarchical import (
        mesh_bookkeeping_report)
    book_16x16 = mesh_bookkeeping_report(make_mesh_spec([16, 16]))
    book_3d = mesh_bookkeeping_report(make_mesh_spec(
        [4, 2, 2], ["data0", "model", "pipe"],
        axis_roles=["data", "model", "pipe"]))
    mesh3d_bookkeeping_ok = bool(book_16x16["ok"] and book_3d["ok"])
    fused_16dev = facts16.get("fused_bitwise", {}) \
        if isinstance(facts16, dict) else {}
    fused_16dev_parity = bool(fused_16dev.get("gather_matmul")
                              and fused_16dev.get("qrs_exchange"))
    rows.append({
        "phase": "fused-mesh3d",
        "bookkeeping_16x16": book_16x16,
        "bookkeeping_3d": book_3d,
        "bookkeeping_ok": mesh3d_bookkeeping_ok,
        "fused_16dev_bitwise": fused_16dev,
        "fused_16dev_parity": fused_16dev_parity,
    })

    # ---- Domino half-batch all-reduce, through the async-issue helper
    from hcache_deepspeed_tpu.runtime.domino import domino_split_async
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("tensor",))
    xd = jnp.asarray(rng.normal(size=(8, 16, 64)), jnp.float32)
    w1 = jnp.asarray(rng.normal(size=(64, 32)), jnp.float32)
    w2 = jnp.asarray(rng.normal(size=(32, 64)), jnp.float32)

    def domino_fn(overlap):
        def fn(x, a, b):
            return domino_split_async(
                lambda h: jax.nn.gelu(h @ a) @ b,
                lambda t: jax.lax.psum(t, "tensor"),
                x, overlap=overlap)
        return fn

    for overlap in (True, False):
        compiled = jax.jit(jax.shard_map(
            domino_fn(overlap), mesh=mesh,
            in_specs=(P(), P(None, "tensor"), P("tensor",)),
            out_specs=P(), check_vma=False)).lower(xd, w1, w2).compile()
        drep = audit_compiled(compiled)
        drow = drep.to_row()
        drow.update({"phase": "domino-audit", "overlap": overlap,
                     "helper": "domino_split_async"})
        rows.append(drow)

    # opt-in int8 wire for the Domino half-batch all-reduces: the
    # compiled module's collective buffers go s8/u8 (wire_bytes shows
    # the quantized portion) while the program stays overlappable
    def domino_q(x, a, b):
        y, _ = domino_split_async(
            lambda h: jax.nn.gelu(h @ a) @ b,
            lambda t: jax.lax.psum(t, "tensor"),
            x, overlap=True, wire_bits=8, axis="tensor")
        return y

    comms.reset()
    compiled = jax.jit(jax.shard_map(
        domino_q, mesh=mesh,
        in_specs=(P(), P(None, "tensor"), P("tensor",)),
        out_specs=P(), check_vma=False)).lower(xd, w1, w2).compile()
    drep = audit_compiled(compiled)
    drow = drep.to_row()
    drow.update({"phase": "domino-audit-int8", "overlap": True,
                 "helper": "domino_split_async",
                 "wire_savings": comms.wire_savings_summary()})
    rows.append(drow)

    # decomposed RS+AG rings for the half-batch all-reduces: the 2
    # derived-legal pairs overlap WITHOUT native async support — every
    # permute step of one half's ring is dependence-free of the other
    # half's dots by dataflow construction
    def domino_dec(x, a, b):
        return domino_split_async(
            lambda h: jax.nn.gelu(h @ a) @ b,
            lambda t: jax.lax.psum(t, "tensor"),
            x, overlap=True, collective_impl="decomposed",
            axis="tensor")

    comms.reset()
    compiled_dec = jax.jit(jax.shard_map(
        domino_dec, mesh=mesh,
        in_specs=(P(), P(None, "tensor"), P("tensor",)),
        out_specs=P(), check_vma=False)).lower(xd, w1, w2).compile()
    drep_dec = audit_compiled(compiled_dec)
    y_native = np.asarray(jax.jit(jax.shard_map(
        domino_fn(True), mesh=mesh,
        in_specs=(P(), P(None, "tensor"), P("tensor",)),
        out_specs=P(), check_vma=False))(xd, w1, w2))
    y_dec = np.asarray(compiled_dec(xd, w1, w2))
    domino_dec_pairs = len(drep_dec.pairs("collective-permute",
                                          min_interleaved=1))
    domino_dec_parity = bool(np.allclose(y_dec, y_native,
                                         rtol=1e-5, atol=1e-5))
    drow = drep_dec.to_row()
    drow.update({"phase": "domino-audit-decomposed", "overlap": True,
                 "helper": "domino_split_async",
                 "overlapped_pairs": domino_dec_pairs,
                 "value_parity_vs_native": domino_dec_parity,
                 "ring_permute_bytes": comms.permute_bytes_summary()})
    rows.append(drow)

    # hierarchical mesh rings for the half-batch all-reduces: the same
    # scheduler-independent overlap on the declared 2x4 factoring of
    # the tensor axis, with per-axis byte attribution
    from hcache_deepspeed_tpu.comm.hierarchical import make_mesh_spec
    domino_spec = make_mesh_spec([2, 4])

    def domino_hier(x, a, b):
        return domino_split_async(
            lambda h: jax.nn.gelu(h @ a) @ b,
            lambda t: jax.lax.psum(t, "tensor"),
            x, overlap=True, collective_impl="hierarchical",
            axis="tensor", mesh_spec=domino_spec)

    comms.reset()
    compiled_hier = jax.jit(jax.shard_map(
        domino_hier, mesh=mesh,
        in_specs=(P(), P(None, "tensor"), P("tensor",)),
        out_specs=P(), check_vma=False)).lower(xd, w1, w2).compile()
    drep_hier = audit_compiled(compiled_hier)
    y_hier = np.asarray(compiled_hier(xd, w1, w2))
    domino_hier_pairs = len(drep_hier.pairs("collective-permute",
                                            min_interleaved=1))
    domino_hier_parity = bool(np.allclose(y_hier, y_native,
                                          rtol=1e-5, atol=1e-5))
    domino_hier_bitwise_flat = bool(np.array_equal(y_hier, y_dec))
    drow = drep_hier.to_row()
    drow.update({"phase": "domino-audit-hierarchical", "overlap": True,
                 "helper": "domino_split_async",
                 "mesh_spec": domino_spec.describe(),
                 "overlapped_pairs": domino_hier_pairs,
                 "value_parity_vs_native": domino_hier_parity,
                 "bitwise_vs_flat_rings": domino_hier_bitwise_flat,
                 "ring_permute_axis_bytes": comms.permute_axis_bytes()})
    rows.append(drow)

    summary = {
        "phase": "summary",
        "metric": "zero3 2-layer toy: overlappable all-gather pairs "
                  "(prefetch on)",
        "value": len(on_pairs),
        "unit": "pairs",
        "prefetch_on_gather_pairs": len(on_pairs),
        "prefetch_off_gather_pairs": len(off_pairs),
        "gather_overlap_ratio_on": on["gather_overlap_ratio"],
        "gather_overlap_ratio_off": off["gather_overlap_ratio"],
        "reduce_overlap_ratio_on": on["reduce_overlap_ratio"],
        "reduce_overlap_ratio_off": off["reduce_overlap_ratio"],
        "native_async_pairs": on["native_async_pairs"],
        "bitwise_parity": bitwise,
        "qrs_wire_fraction_of_fp32": qrs_frac,
        "qrs_bitwise_depth_parity": q_bitwise,
        "qrs_trajectory_within_tol": traj_ok,
        "structural_overlap_ratio_decomposed": structural,
        "structural_overlap_ratio_decomposed_qwire": dq_structural,
        "decomposed_bitwise_vs_native": dec_bitwise,
        "decomposed_qwire_bitwise": dq_bitwise,
        "decomposed_structural_ge_native_gather": bool(
            structural >= on["gather_overlap_ratio"]),
        "decomposed_structural_ge_native_reduce": bool(
            structural >= on["reduce_overlap_ratio"]),
        "domino_decomposed_overlapped_pairs": domino_dec_pairs,
        "domino_decomposed_value_parity": domino_dec_parity,
        "hier_bitwise_vs_native": hier_bitwise_native,
        "hier_bitwise_vs_flat": hier_bitwise_flat,
        "hier_fullwidth_bitwise_vs_flat": hier_fw_bitwise_flat,
        "hier_qwire_bitwise": hier_qwire_bitwise,
        "hier_structural_overlap_ratio": hier_structural,
        "hier_structural_ge_flat": bool(hier_structural >= structural),
        "hier_interaxis_wire_fraction": hier_interaxis_fraction,
        "hier_longhaul_gather_fraction": lh_frac,
        "hier_longhaul_trajectory_within_tol": lh_traj_ok,
        "hier_pod_wire_seconds_inter": hier_cost_pod["per_axis"]
        .get("inter", {}).get("seconds"),
        "hier_pod_wire_seconds_intra": hier_cost_pod["per_axis"]
        .get("intra", {}).get("seconds"),
        "hier_pod_bottleneck_axis": hier_cost_pod["bottleneck_axis"],
        "domino_hier_overlapped_pairs": domino_hier_pairs,
        "domino_hier_value_parity": domino_hier_parity,
        # ISSUE 15: unified hpZ tiering, phase pipelining, 16-device
        # factorings, measured wire calibration
        "hier_hpz_unified_bitwise": hpz_unified_bitwise,
        "hier_hpz_fullwidth_bitwise": hpz_fw_bitwise,
        "hier_hpz_qw_bitwise": hpz_qw_bitwise,
        "hier_hpz_secondary_on_mesh": hpz_secondary_on_mesh,
        "hier_pipelined_bitwise": pipelined_bitwise,
        "hier_pipelined_structural_ratio": pipelined_structural,
        "hier_pipelined_cross_axis_pairs": prim_cross[2]["pairs"],
        "hier_unpipelined_cross_axis_pairs": prim_cross[1]["pairs"],
        "hier_16dev_parity": hier_16dev_parity,
        # ISSUE 18: fused computation-collective kernels + 3-D mesh
        "fused_parity_plain": fused_parity_plain,
        "fused_parity_qwire": fused_parity_qwire,
        "fused_bitwise_vs_hier": fused_bitwise_hier,
        "fused_mid_gather_leaves": fused_mid_gather_leaves,
        "fused_subsumed_pairs": fused_subsumed,
        "unfused_subsumed_pairs": unfused_subsumed,
        "fused_audit_gate": fused_audit_gate,
        "fused_wallclock_speedup": fused_wallclock_speedup,
        "fused_le_unfused_largest": fused_le_unfused_largest,
        "mesh3d_bookkeeping_ok": mesh3d_bookkeeping_ok,
        "fused_16dev_parity": fused_16dev_parity,
        "fused_fallbacks": fb["fused_fallbacks"],
        "wire_cal_shape_ok": wire_cal_shape_ok,
        "wire_cal_gbps_inter": cal["gbytes_per_s"].get("inter"),
        "wire_cal_gbps_intra": cal["gbytes_per_s"].get("intra"),
        "wire_cal_divergence_inter":
            cal["divergence_vs_declared"].get("inter"),
        "wire_cal_divergence_intra":
            cal["divergence_vs_declared"].get("intra"),
        "pod_shape": pod_arg,
        "wire_saved_bytes_per_op": {
            op: rec["saved_bytes"]
            for op, rec in qrs_row["wire_savings"].items()},
        "backend": jax.default_backend(),
        "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    rows.append(summary)
    # regression sentinel: self-compare against the committed
    # trajectory BEFORE writing — the verdicts ride in the artifact
    # (non-fatal here; `perf check` is the gate with an exit code)
    from hcache_deepspeed_tpu.perf import self_check_rows
    check_row = self_check_rows(out_path, rows)
    rows.append(check_row)
    if check_row.get("regressions"):
        print(f"[bench] perf-check: {len(check_row['regressions'])} "
              f"headline regression(s) vs committed trajectory: "
              + "; ".join(r["metric"]
                          for r in check_row["regressions"]),
              file=sys.stderr)
    with open(out_path, "w") as fh:
        for row in rows:
            fh.write(json.dumps(row) + "\n")
    _DONE.set()
    print(json.dumps({
        "metric": summary["metric"], "value": summary["value"],
        "unit": "pairs",
        "vs_baseline": 0.0 if not bitwise else 1.0,
        "extra": {k: v for k, v in summary.items()
                  if k not in ("phase", "metric", "value", "unit")},
    }), flush=True)
    ok = (len(on_pairs) >= 1 and len(off_pairs) == 0 and bitwise
          and q_bitwise and traj_ok
          and qrs_frac is not None and qrs_frac <= 0.35
          and dec_bitwise and dq_bitwise
          and structural >= on["gather_overlap_ratio"]
          and structural >= on["reduce_overlap_ratio"]
          and domino_dec_pairs >= 2 and domino_dec_parity
          # hierarchical gates (ISSUE 12): bitwise vs native AND flat
          # for plain + quantized wire, inter-axis quantized bytes
          # <= 0.35x full width, structural >= the flat rings
          and hier_bitwise_native and hier_bitwise_flat
          and hier_fw_bitwise_flat and hier_qwire_bitwise
          and hier_interaxis_fraction is not None
          and hier_interaxis_fraction <= 0.35
          and hier_structural >= structural
          and lh_frac is not None and lh_frac <= 0.35 and lh_traj_ok
          and domino_hier_pairs >= 2 and domino_hier_parity
          and domino_hier_bitwise_flat
          # ISSUE 15 gates: unified hpZ bitwise (fullwidth + qwZ
          # transport swaps), secondary refresh attributed on the
          # mesh, pipelined bitwise + structural >= the PR 12 number
          # + cross-axis pairs only in the pipelined program, the
          # 16-device (4x4 / 2x8) parity leg, and a shape-valid
          # measured calibration row
          and hpz_unified_bitwise and hpz_secondary_on_mesh
          and pipelined_bitwise and pipelined_structural >= structural
          and pipelined_cross_ok
          and hier_16dev_parity and wire_cal_shape_ok
          # ISSUE 18 gates: fused engine bitwise on plain + quantized
          # wire with mid-gather leaves actually routed, the in-kernel
          # audit differential (fused >= 1 subsumed pair, unfused 0),
          # fused <= unfused at the largest rig payload, 3-D mesh
          # bookkeeping, and the 16-dev fused parity legs
          and fused_parity_plain and fused_parity_qwire
          and fused_mid_gather_leaves >= 1
          and fused_audit_gate and fused_le_unfused_largest
          and mesh3d_bookkeeping_ok and fused_16dev_parity)
    return 0 if ok else 4


def run_fleet(out_path="FLEET_SERVE.jsonl"):
    """``--fleet``: CPU-deterministic fleet-serving audit — the
    N-replica router + latent-based KV migration stack under seeded
    replica crash/hang/partition chaos on the shared virtual clock
    (docs/serving.md / docs/resilience.md). Emits per-replica
    occupancy, per-migration rows and a summary with the span-derived
    migration/decode overlap ratio; self-compares against the
    committed perf trajectory before writing, like the zero-overlap
    and serve_loop phases. Never touches the TPU relay."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    from hcache_deepspeed_tpu.inference.benchmark import \
        run_fleet_serve
    try:
        results = run_fleet_serve(out=out_path)
    except RuntimeError as exc:
        print(json.dumps(_error_payload(f"fleet gate failed: {exc}")),
              flush=True)
        _DONE.set()
        return 4
    summary = next(r for r in results
                   if r.get("phase") == "fleet-summary")
    _DONE.set()
    print(json.dumps({
        "metric": "fleet chaos: latent migrations landed "
                  "(crash/hang/partition survived)",
        "value": summary["landings"] + summary["recompute_landings"],
        "unit": "migrations",
        "vs_baseline": 1.0 if summary["invariants_ok"] and
        summary["deterministic"] else 0.0,
        "extra": {k: summary[k] for k in
                  ("deterministic", "invariants_ok",
                   "migration_balance_ok", "evictions",
                   "migration_overlap_ratio", "span_overlap_ratio",
                   "replica_crashes", "replica_states")},
    }), flush=True)
    ok = (summary["invariants_ok"] and summary["deterministic"] and
          summary["migration_balance_ok"] and
          summary["span_counter_agreement"])
    return 0 if ok else 4


def run_disagg(out_path="DISAGG_SERVE.jsonl"):
    """``--disagg``: CPU-deterministic disaggregated-serving audit —
    the N-prefill + M-decode tier coordinator with latent-wire handoff
    vs an equal-replica colocated fleet on the shared virtual clock
    (docs/serving.md). Gates inline: decode-tier TPOT p99 strictly
    better than the colocated baseline, bitwise stream parity,
    span-derived handoff/decode overlap agreeing with the counters,
    byte-identical same-seed digests, int8-wire parity, chunked
    prefill accounting, and tier-scoped chaos invariants. Self-
    compares against the committed perf trajectory before writing.
    Never touches the TPU relay."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    from hcache_deepspeed_tpu.inference.benchmark import \
        run_disagg_serve
    try:
        results = run_disagg_serve(out=out_path)
    except RuntimeError as exc:
        print(json.dumps(_error_payload(f"disagg gate failed: {exc}")),
              flush=True)
        _DONE.set()
        return 4
    summary = next(r for r in results
                   if r.get("phase") == "disagg-summary")
    _DONE.set()
    print(json.dumps({
        "metric": "disagg serving: decode-tier TPOT p99 vs "
                  "colocated baseline (equal replicas)",
        "value": round(summary["colocated_tpot_p99"] /
                       max(summary["decode_tier_tpot_p99"], 1e-12),
                       4),
        "unit": "x better",
        "vs_baseline": 1.0 if summary["invariants_ok"] and
        summary["deterministic"] else 0.0,
        "extra": {k: summary[k] for k in
                  ("deterministic", "stream_parity", "invariants_ok",
                   "handoffs", "colocated_decodes",
                   "handoff_overlap_ratio", "span_counter_agreement",
                   "decode_tier_tpot_p99", "colocated_tpot_p99")},
    }), flush=True)
    ok = (summary["invariants_ok"] and summary["deterministic"] and
          summary["stream_parity"] and
          summary["span_counter_agreement"] and
          summary["decode_tier_tpot_p99"] <
          summary["colocated_tpot_p99"])
    return 0 if ok else 4


def run_spec_serve(out_path="SPEC_SERVE.jsonl"):
    """``--spec-serve``: CPU-deterministic audit of scheduler-
    dispatched speculative decoding + fleet-wide radix prefix reuse
    with latent prefix broadcast (docs/serving.md). Gates inline:
    bitwise stream parity vs non-speculative greedy, accepted-tokens/
    step > 1.3 on the lookup-friendly trace, >= 1 latent prefix
    broadcast with positive re-prefill savings on the affinity-vs-
    load conflict trace, the SLO-aware ladder escalating under an
    unmeetable objective, and byte-identical two-run event digests.
    Self-compares against the committed perf trajectory before
    writing. Never touches the TPU relay."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    from hcache_deepspeed_tpu.inference.benchmark import \
        run_spec_serve as run_ss
    try:
        results = run_ss(out=out_path)
    except RuntimeError as exc:
        print(json.dumps(_error_payload(
            f"spec-serve gate failed: {exc}")), flush=True)
        _DONE.set()
        return 4
    summary = next(r for r in results
                   if r.get("phase") == "spec-serve-summary")
    _DONE.set()
    print(json.dumps({
        "metric": "speculative serving: accepted tokens per "
                  "speculative lane-step (1.0 = non-speculative "
                  "floor)",
        "value": summary["accepted_tokens_per_step"],
        "unit": "tokens/step",
        "vs_baseline": 1.0 if summary["invariants_ok"] and
        summary["deterministic"] else 0.0,
        "extra": {k: summary[k] for k in
                  ("deterministic", "stream_parity",
                   "lookup_virtual_speedup", "mixed_virtual_speedup",
                   "reprefill_savings", "prefix_broadcasts",
                   "prefix_tokens_reused", "slo_final_level")},
    }), flush=True)
    ok = (summary["invariants_ok"] and summary["deterministic"] and
          summary["stream_parity"] and
          summary["accepted_tokens_per_step"] > 1.3 and
          summary["reprefill_savings"] > 0)
    return 0 if ok else 4


def run_fabric(out_path="FABRIC_SERVE.jsonl"):
    """``--fabric``: deployment-fabric audit — the seeded migration-
    heavy trace served through both replica transports (in-memory
    twin vs spawned worker processes shipping real bytes over real
    sockets; docs/fabric.md), plus the literal kill-a-process chaos
    leg. Gates inline: two-run digest determinism on the in-memory
    twin, digest invariance and bitwise token-stream parity across
    transports, >= 1 two-hop worker-to-worker crossing, measured wire
    throughput recorded beside the priced link, >= 2 trace hops
    across real process boundaries with a connected causal DAG, and
    crash recovery with never-dropped accounting. Self-compares
    against the committed perf trajectory before writing. Never
    touches the TPU relay."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    from hcache_deepspeed_tpu.inference.benchmark import \
        run_fabric_serve
    try:
        results = run_fabric_serve(out=out_path)
    except RuntimeError as exc:
        print(json.dumps(_error_payload(
            f"fabric gate failed: {exc}")), flush=True)
        _DONE.set()
        return 4
    summary = next(r for r in results
                   if r.get("phase") == "fabric-summary")
    _DONE.set()
    print(json.dumps({
        "metric": "deployment fabric: real-wire deliveries with "
                  "digest/stream parity vs the in-memory twin",
        "value": summary["two_hop_deliveries"],
        "unit": "two-hop crossings",
        "vs_baseline": 1.0 if summary["invariants_ok"] and
        summary["deterministic"] else 0.0,
        "extra": {k: summary[k] for k in
                  ("deterministic", "digest_transport_invariant",
                   "stream_parity", "max_trace_hops",
                   "trace_connected", "measured_wire_bytes_per_s",
                   "priced_link_bytes_per_s", "chaos_ok",
                   "chaos_kills", "replica_crashes",
                   "bootstrap_mismatches")},
    }), flush=True)
    ok = (summary["invariants_ok"] and summary["deterministic"] and
          summary["stream_parity"] and
          summary["digest_transport_invariant"] and
          summary["chaos_ok"])
    return 0 if ok else 4


def run_fabric_obs(out_path="FABRIC_OBS.jsonl"):
    """``--fabric-obs``: cross-process telemetry-plane audit — worker
    span/metric harvest over the fabric control channel, assembled
    process-fleet timelines, SIGKILL postmortem telemetry, per-link
    wire percentiles (docs/observability.md). Gates inline: harvest
    on/off digest invariance against the in-memory twin, 2-run
    determinism, Perfetto-clean cross-process timeline with >= 1
    arrow spanning two real worker processes, the killed worker's
    last-harvested telemetry in the flight bundle, and harvest
    overhead <= 5% of the fabric leg. Self-compares against the
    committed perf trajectory before writing. Never touches the TPU
    relay."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    from hcache_deepspeed_tpu.inference.benchmark import \
        run_fabric_obs as run_fo
    try:
        results = run_fo(out=out_path)
    except RuntimeError as exc:
        print(json.dumps(_error_payload(
            f"fabric-obs gate failed: {exc}")), flush=True)
        _DONE.set()
        return 4
    summary = next(r for r in results
                   if r.get("phase") == "fabric-obs-summary")
    _DONE.set()
    print(json.dumps({
        "metric": "cross-process telemetry plane: harvested worker "
                  "spans on a digest-invisible control channel",
        "value": summary["worker_spans"],
        "unit": "harvested spans",
        "vs_baseline": 1.0 if summary["invariants_ok"] and
        summary["harvest_digest_invariant"] else 0.0,
        "extra": {k: summary[k] for k in
                  ("deterministic", "harvest_digest_invariant",
                   "timeline_valid", "worker_rows",
                   "cross_worker_arrows",
                   "postmortem_has_telemetry",
                   "harvest_overhead_fraction", "harvests",
                   "chaos_ok", "busiest_link")},
    }), flush=True)
    ok = (summary["invariants_ok"] and summary["deterministic"] and
          summary["harvest_digest_invariant"] and
          summary["timeline_valid"] and
          summary["postmortem_has_telemetry"] and
          summary["chaos_ok"])
    return 0 if ok else 4


def run_request_trace(out_path="REQUEST_TRACE.jsonl"):
    """``--request-trace``: CPU-deterministic causal-tracing audit —
    replay the chaos/fleet/disagg workloads and gate connected
    cross-replica span DAGs, per-request attribution closure (sum ==
    measured E2E within 1%), same-seed digest determinism, and
    byte-identical flight-recorder bundle digests
    (docs/observability.md). Self-compares against the committed perf
    trajectory before writing. Never touches the TPU relay."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    from hcache_deepspeed_tpu.inference.benchmark import \
        run_request_trace as run_rt
    try:
        results = run_rt(out=out_path)
    except RuntimeError as exc:
        print(json.dumps(_error_payload(
            f"request-trace gate failed: {exc}")), flush=True)
        _DONE.set()
        return 4
    summary = next(r for r in results
                   if r.get("phase") == "request-trace-summary")
    _DONE.set()
    print(json.dumps({
        "metric": "causal request tracing: traced requests with "
                  "connected DAGs + closed attribution",
        "value": summary["traced_requests"],
        "unit": "requests",
        "vs_baseline": 1.0 if summary["dag_connected"] and
        summary["closure_ok"] else 0.0,
        "extra": {k: summary[k] for k in
                  ("dag_connected", "closure_ok",
                   "closure_max_residual", "deterministic",
                   "flight_deterministic", "flight_bundles",
                   "crash_evacuations", "handoffs",
                   "ttft_attr_p99_s")},
    }), flush=True)
    ok = (summary["dag_connected"] and summary["closure_ok"] and
          summary["deterministic"] and
          summary["flight_deterministic"] and
          not summary["violations"])
    return 0 if ok else 4


def run_autoscale(out_path="AUTOSCALE_SERVE.jsonl"):
    """``--autoscale``: SLO-driven elastic autoscaling audit — the
    hysteresis control loop over the bursty diurnal multi-tenant
    trace, with scale events as a first-class failure domain
    (docs/serving.md). Gates inline: 2-run digest determinism with
    the autoscaler active, SLO attainment >= the best static fleet of
    equal peak size at strictly lower replica-step cost, every scale
    event span-verified through the causal trace DAG, scale-event
    chaos (aborted bootstrap / mid-drain crash / faulted pre-warm)
    with byte-identical replays, and a process-mode leg where a real
    worker is spawned by scale-up (first spawn killed and recovered)
    and reaped on drain-retirement. Self-compares against the
    committed perf trajectory before writing. Never touches the TPU
    relay."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    from hcache_deepspeed_tpu.inference.benchmark import \
        run_autoscale_serve
    try:
        results = run_autoscale_serve(out=out_path)
    except RuntimeError as exc:
        print(json.dumps(_error_payload(
            f"autoscale gate failed: {exc}")), flush=True)
        _DONE.set()
        return 4
    summary = next(r for r in results
                   if r.get("phase") == "autoscale-summary")
    _DONE.set()
    print(json.dumps({
        "metric": "elastic autoscaling: SLO attainment at lower cost "
                  "than the equal-peak static fleet",
        "value": summary["slo_attainment"],
        "unit": "attainment fraction",
        "vs_baseline": 1.0 if summary["invariants_ok"] and
        summary["deterministic"] else 0.0,
        "extra": {k: summary[k] for k in
                  ("deterministic", "slo_vs_static_ok",
                   "cost_vs_static_ok", "cost_savings_fraction",
                   "cost_replica_steps", "static_peak_cost",
                   "scale_ups", "retires_completed", "flaps",
                   "scale_events_span_verified",
                   "chaos_deterministic", "chaos_invariants_ok",
                   "process_ok", "trace_connected")},
    }), flush=True)
    ok = (summary["invariants_ok"] and summary["deterministic"] and
          summary["slo_vs_static_ok"] and
          summary["cost_vs_static_ok"] and
          summary["chaos_invariants_ok"] and summary["process_ok"])
    return 0 if ok else 4


def main():
    if "--zero-overlap" in sys.argv[1:]:
        return run_zero_overlap()
    if "--fleet" in sys.argv[1:]:
        return run_fleet()
    if "--disagg" in sys.argv[1:]:
        return run_disagg()
    if "--spec-serve" in sys.argv[1:]:
        return run_spec_serve()
    if "--fabric-obs" in sys.argv[1:]:
        return run_fabric_obs()
    if "--fabric" in sys.argv[1:]:
        return run_fabric()
    if "--request-trace" in sys.argv[1:]:
        return run_request_trace()
    if "--autoscale" in sys.argv[1:]:
        return run_autoscale()
    child = os.environ.get("HDS_BENCH_CHILD")
    if child or os.environ.get("HDS_BENCH_TINY") == "1":
        # child / smoke mode: measure exactly one config in-process
        watchdog = _arm_watchdog()
        run_config(child or CANDIDATES[-1])
        watchdog.cancel()
        return 0

    watchdog = _arm_watchdog()
    deadline = time.monotonic() + _WATCHDOG_SECS - 60
    results = []
    names = list(CANDIDATES)
    probe = _probe_relay()
    if probe != "up":
        # Dead relay / wedged compile service. One rescue attempt (only
        # on a hang — a fast "no-tpu" failure means execution is just as
        # dead and run_config's CPU-refusal guard would reject anyway):
        # the winner's executable is in the LOCAL cache (cfg["compile"]),
        # so if execution is alive it can still measure without touching
        # the remote compiler; cap it so the whole dead-relay path stays
        # under ~8 minutes instead of round-4's 29.
        rescue_budget = deadline - time.monotonic()
        result = None
        if probe == "timeout" and rescue_budget >= 60:
            print(f"[bench] relay probe hung ({_PROBE_SECS:.0f}s); trying "
                  "the locally-cached winner once, then reporting stale",
                  file=sys.stderr)
            result, _ = _run_candidate_subprocess(
                CANDIDATES[0], min(300.0, rescue_budget))
        else:
            print(f"[bench] relay probe: {probe}; skipping rescue "
                  f"(budget {rescue_budget:.0f}s)", file=sys.stderr)
        _DONE.set()
        watchdog.cancel()
        if result is not None:
            print(json.dumps(result), flush=True)
            return 0
        reason = ("no TPU backend (CPU fallback / mis-set env)"
                  if probe == "no-tpu" else
                  "TPU relay unresponsive and cached-winner rescue failed")
        payload = _error_payload(f"no fresh measurement: {reason}")
        print(json.dumps(payload), flush=True)
        return _error_exit_code(payload)
    while names:
        name = names.pop(0)
        last = not names
        remaining = deadline - time.monotonic()
        if last:
            timeout = remaining
        else:
            timeout = min(_CAND_SECS, remaining - _LAST_RESERVE)
        if timeout <= 60:
            print(f"[bench] skipping {name}: budget exhausted",
                  file=sys.stderr)
            continue
        result, timed_out = _run_candidate_subprocess(name, timeout)
        if result is not None:
            results.append(result)
        elif timed_out and not last:
            # the wedge signature: nothing new will compile — jump
            # straight to the cache-proven config
            print("[bench] compile service looks wedged; skipping to "
                  "the cached config", file=sys.stderr)
            names = names[-1:]
    _DONE.set()
    watchdog.cancel()
    if results:
        best = max(results, key=lambda r: (r.get("extra", {}).get("mfu", 0.0),
                                           r.get("value", 0.0)))
        print(json.dumps(best), flush=True)
        return 0
    payload = _error_payload(
        "no candidate produced a result (TPU relay down?)")
    print(json.dumps(payload), flush=True)
    return _error_exit_code(payload)


if __name__ == "__main__":
    sys.exit(main())
