// Indexed token dataset + prefetching batch loader (C ABI, ctypes).
//
// The data-pipeline IO layer: a memory-mapped binary token stream with a
// document index, and a background-thread loader that materializes
// shuffled fixed-length LM samples into double-buffered batches so the
// host never stalls the accelerator step loop on disk reads.
// Reference analog: deepspeed/runtime/data_pipeline (python-side
// sampling) + the Megatron-style indexed dataset its examples train
// from; native here per the build plan's "IO stays C++" stance
// (SURVEY.md 2.5 #7 note).
//
// .idx layout (little endian):
//   8 bytes  magic "HDSIDX1\0"
//   u32      dtype code (2 = uint16, 4 = int32)
//   u32      reserved (0)
//   u64      n_docs
//   u64[n_docs+1] cumulative token offsets (offs[0] = 0)
// .bin: the raw token stream, n_tokens * dtype_size bytes.
//
// Sampling model: the stream is cut into floor((n_tokens-1)/seq) chunks
// of seq+1 overlapping-by-one tokens (input/label shift); each epoch
// visits every chunk once in an order given by a SplitMix64-keyed
// Fisher-Yates shuffle, reproducible in python (see
// runtime/data/indexed_dataset.py _permutation).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr char kMagic[8] = {'H', 'D', 'S', 'I', 'D', 'X', '1', '\0'};

struct Dataset {
  int fd = -1;
  const uint8_t* bin = nullptr;   // mmap'd token stream
  size_t bin_bytes = 0;
  uint32_t dtype = 0;             // 2 = uint16, 4 = int32
  std::vector<uint64_t> offs;     // cumulative token offsets
};

uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

// Fisher-Yates keyed by SplitMix64 — identical to the python fallback.
void permutation(uint64_t n, uint64_t seed, std::vector<uint64_t>* out) {
  out->resize(n);
  for (uint64_t i = 0; i < n; ++i) (*out)[i] = i;
  for (uint64_t i = n; i > 1; --i) {
    uint64_t j = splitmix64(seed ^ (i - 1)) % i;
    std::swap((*out)[i - 1], (*out)[j]);
  }
}

inline int32_t token_at(const Dataset* ds, uint64_t i) {
  if (ds->dtype == 2) {
    uint16_t v;
    std::memcpy(&v, ds->bin + i * 2, 2);
    return static_cast<int32_t>(v);
  }
  int32_t v;
  std::memcpy(&v, ds->bin + i * 4, 4);
  return v;
}

struct Loader {
  const Dataset* ds = nullptr;
  uint64_t seq = 0, batch = 0, seed = 0;
  uint64_t n_chunks = 0;
  uint64_t sample_len = 0;        // seq + 1

  // producer state
  std::vector<uint64_t> order;
  uint64_t epoch = 0, cursor = 0;

  // ring of prepared batches
  struct Slot {
    std::vector<int32_t> data;    // [batch, seq+1]
    uint64_t epoch = 0;
    bool full = false;
  };
  std::vector<Slot> ring;
  size_t head = 0, tail = 0;      // head: consumer, tail: producer
  std::mutex mu;
  std::condition_variable cv_full, cv_empty;
  std::atomic<bool> stop{false};
  std::thread worker;

  void fill_one(Slot* slot) {
    slot->data.resize(batch * sample_len);
    for (uint64_t b = 0; b < batch; ++b) {
      if (cursor == n_chunks) {
        ++epoch;
        cursor = 0;
        permutation(n_chunks, seed + epoch, &order);
      }
      uint64_t chunk = order[cursor++];
      uint64_t base = chunk * seq;           // sample_len tokens from here
      int32_t* dst = slot->data.data() + b * sample_len;
      for (uint64_t t = 0; t < sample_len; ++t)
        dst[t] = token_at(ds, base + t);
    }
    slot->epoch = epoch;
  }

  void run() {
    for (;;) {
      std::unique_lock<std::mutex> lk(mu);
      cv_empty.wait(lk, [&] { return stop.load() || !ring[tail].full; });
      if (stop.load()) return;
      Slot* slot = &ring[tail];
      lk.unlock();
      fill_one(slot);              // disk/mmap work outside the lock
      lk.lock();
      slot->full = true;
      tail = (tail + 1) % ring.size();
      cv_full.notify_one();
    }
  }
};

}  // namespace

extern "C" {

void* hds_idx_open(const char* prefix) {
  std::string p(prefix);
  FILE* f = std::fopen((p + ".idx").c_str(), "rb");
  if (!f) return nullptr;
  char magic[8];
  uint32_t dtype = 0, reserved = 0;
  uint64_t n_docs = 0;
  bool ok = std::fread(magic, 1, 8, f) == 8 &&
            std::memcmp(magic, kMagic, 8) == 0 &&
            std::fread(&dtype, 4, 1, f) == 1 &&
            std::fread(&reserved, 4, 1, f) == 1 &&
            std::fread(&n_docs, 8, 1, f) == 1 &&
            (dtype == 2 || dtype == 4);
  if (ok) {
    // n_docs comes from the file: bound it by the file's actual size
    // (24-byte header + 8 * (n_docs + 1) offsets) BEFORE resize —
    // a wrapped n_docs+1 or a bad_alloc must not escape into ctypes
    long pos = std::ftell(f);
    ok = pos == 24 && std::fseek(f, 0, SEEK_END) == 0;
    if (ok) {
      long end = std::ftell(f);
      ok = end >= 0 &&
           static_cast<uint64_t>(end - 24) / 8 >= 1 &&
           n_docs == static_cast<uint64_t>(end - 24) / 8 - 1;
    }
    ok = ok && std::fseek(f, 24, SEEK_SET) == 0;
  }
  auto* ds = new Dataset();
  if (ok) {
    ds->offs.resize(n_docs + 1);
    ok = std::fread(ds->offs.data(), 8, n_docs + 1, f) == n_docs + 1;
  }
  std::fclose(f);
  if (ok) {
    // reject corrupt indexes up front: offsets must be monotone, and
    // the total must be small enough that offs.back() * dtype cannot
    // wrap uint64 and defeat the file-size check below
    for (size_t i = 0; ok && i + 1 < ds->offs.size(); ++i)
      ok = ds->offs[i] <= ds->offs[i + 1];
    ok = ok && ds->offs[0] == 0 &&
         ds->offs.back() <= UINT64_MAX / 8;
  }
  if (ok) {
    ds->dtype = dtype;
    ds->fd = ::open((p + ".bin").c_str(), O_RDONLY);
    ok = ds->fd >= 0;
  }
  if (ok) {
    struct stat st;
    ok = ::fstat(ds->fd, &st) == 0 &&
         static_cast<uint64_t>(st.st_size) >= ds->offs.back() * dtype;
    if (ok) {
      ds->bin_bytes = static_cast<size_t>(st.st_size);
      void* m = ::mmap(nullptr, ds->bin_bytes, PROT_READ, MAP_PRIVATE,
                       ds->fd, 0);
      ok = m != MAP_FAILED;
      if (ok) ds->bin = static_cast<const uint8_t*>(m);
    }
  }
  if (!ok) {
    if (ds->fd >= 0) ::close(ds->fd);
    delete ds;
    return nullptr;
  }
  return ds;
}

void hds_idx_close(void* h) {
  auto* ds = static_cast<Dataset*>(h);
  if (!ds) return;
  if (ds->bin) ::munmap(const_cast<uint8_t*>(ds->bin), ds->bin_bytes);
  if (ds->fd >= 0) ::close(ds->fd);
  delete ds;
}

uint64_t hds_idx_num_docs(void* h) {
  return static_cast<Dataset*>(h)->offs.size() - 1;
}

uint64_t hds_idx_total_tokens(void* h) {
  return static_cast<Dataset*>(h)->offs.back();
}

int hds_idx_dtype(void* h) {
  return static_cast<int>(static_cast<Dataset*>(h)->dtype);
}

uint64_t hds_idx_doc_len(void* h, uint64_t i) {
  auto* ds = static_cast<Dataset*>(h);
  return ds->offs[i + 1] - ds->offs[i];
}

void hds_idx_read_doc(void* h, uint64_t i, int32_t* out) {
  auto* ds = static_cast<Dataset*>(h);
  const uint64_t start = ds->offs[i], end = ds->offs[i + 1];
  for (uint64_t t = start; t < end; ++t) *out++ = token_at(ds, t);
}

void* hds_loader_create(void* h, uint64_t seq, uint64_t batch,
                        uint64_t seed, int ring_slots) {
  auto* ds = static_cast<Dataset*>(h);
  const uint64_t total = ds->offs.back();
  if (total < seq + 1 || seq == 0 || batch == 0) return nullptr;
  auto* ld = new Loader();
  ld->ds = ds;
  ld->seq = seq;
  ld->batch = batch;
  ld->seed = seed;
  ld->sample_len = seq + 1;
  ld->n_chunks = (total - 1) / seq;
  ld->ring.resize(ring_slots < 2 ? 2 : ring_slots);
  permutation(ld->n_chunks, ld->seed, &ld->order);
  ld->worker = std::thread([ld] { ld->run(); });
  return ld;
}

// Blocks until a batch is ready; copies [batch, seq+1] int32 into `out`
// and returns the epoch the batch came from.
uint64_t hds_loader_next(void* h, int32_t* out) {
  auto* ld = static_cast<Loader*>(h);
  std::unique_lock<std::mutex> lk(ld->mu);
  ld->cv_full.wait(lk, [&] { return ld->ring[ld->head].full; });
  Loader::Slot* slot = &ld->ring[ld->head];
  std::memcpy(out, slot->data.data(), slot->data.size() * 4);
  uint64_t epoch = slot->epoch;
  slot->full = false;
  ld->head = (ld->head + 1) % ld->ring.size();
  ld->cv_empty.notify_one();
  return epoch;
}

void hds_loader_destroy(void* h) {
  auto* ld = static_cast<Loader*>(h);
  if (!ld) return;
  {
    std::lock_guard<std::mutex> lk(ld->mu);
    ld->stop.store(true);
  }
  ld->cv_empty.notify_all();
  ld->worker.join();
  delete ld;
}

}  // extern "C"
