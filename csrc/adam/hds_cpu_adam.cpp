// SIMD CPU optimizers for host-offloaded states (ZeRO-Offload step).
//
// Reference analog: csrc/adam/cpu_adam_impl.cpp + includes/cpu_adam.h
// (AVX512/AVX2 Step_AVX over flattened fp32 state) and the adagrad/lion
// siblings. Re-design: one C file, C linkage for ctypes, auto-vectorized
// inner loops (gcc -O3 -march=native vectorizes these simple fused loops
// to the same AVX FMA sequence the reference hand-writes with intrinsics)
// + OpenMP-free std::thread row partitioning for large tensors.
//
// All arrays are contiguous fp32 host buffers; `step` is 1-based.

#include <cmath>
#include <cstdint>
#include <thread>
#include <vector>

namespace {

template <typename F>
void parallel_for(int64_t n, F body, int64_t grain = 1 << 16) {
  int hw = static_cast<int>(std::thread::hardware_concurrency());
  int threads = static_cast<int>(
      std::min<int64_t>(hw > 0 ? hw : 4, (n + grain - 1) / grain));
  if (threads <= 1) {
    body(0, n);
    return;
  }
  std::vector<std::thread> ts;
  int64_t chunk = (n + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    int64_t lo = t * chunk, hi = std::min<int64_t>(n, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back([=] { body(lo, hi); });
  }
  for (auto& t : ts) t.join();
}

}  // namespace

extern "C" {

// AdamW (decoupled weight decay, bias-corrected — optax.adamw semantics
// so host and device steps are interchangeable).
void hds_cpu_adam_step(float* params, const float* grads, float* exp_avg,
                       float* exp_avg_sq, int64_t n, float lr, float beta1,
                       float beta2, float eps, float weight_decay,
                       int64_t step) {
  float bc1 = 1.0f - std::pow(beta1, static_cast<float>(step));
  float bc2 = 1.0f - std::pow(beta2, static_cast<float>(step));
  parallel_for(n, [=](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      float g = grads[i];
      float m = beta1 * exp_avg[i] + (1.0f - beta1) * g;
      float v = beta2 * exp_avg_sq[i] + (1.0f - beta2) * g * g;
      exp_avg[i] = m;
      exp_avg_sq[i] = v;
      float mhat = m / bc1;
      float vhat = v / bc2;
      float update = mhat / (std::sqrt(vhat) + eps) +
                     weight_decay * params[i];
      params[i] -= lr * update;
    }
  });
}

void hds_cpu_adagrad_step(float* params, const float* grads, float* state,
                          int64_t n, float lr, float eps,
                          float weight_decay) {
  parallel_for(n, [=](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      float g = grads[i] + weight_decay * params[i];
      float s = state[i] + g * g;
      state[i] = s;
      params[i] -= lr * g / (std::sqrt(s) + eps);
    }
  });
}

void hds_cpu_lion_step(float* params, const float* grads, float* exp_avg,
                       int64_t n, float lr, float beta1, float beta2,
                       float weight_decay) {
  parallel_for(n, [=](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      float g = grads[i];
      float m = exp_avg[i];
      float c = beta1 * m + (1.0f - beta1) * g;
      float sign = c > 0.0f ? 1.0f : (c < 0.0f ? -1.0f : 0.0f);
      params[i] -= lr * (sign + weight_decay * params[i]);
      exp_avg[i] = beta2 * m + (1.0f - beta2) * g;
    }
  });
}

}  // extern "C"
