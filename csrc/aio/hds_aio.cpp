// Async file I/O thread pool for host<->NVMe tensor swapping.
//
// Reference analog: csrc/aio/ (DeepNVMe) — there a libaio event loop with
// pinned-buffer management behind pybind11 (py_ds_aio.cpp). TPU-VM
// re-design: a plain C API (ctypes-friendly, no pybind11 dependency) over
// a worker-thread pool issuing pread/pwrite with O_DIRECT-free buffered
// I/O — on GCP TPU-VM local SSDs the kernel page cache + parallel streams
// saturate the device without libaio, and the same binary runs anywhere.
//
// API (all functions exported with C linkage):
//   hds_aio_create(num_threads, queue_depth)      -> handle id
//   hds_aio_submit_read(h, path, buf, n, offset)  -> request id
//   hds_aio_submit_write(h, path, buf, n, offset) -> request id
//   hds_aio_wait(h, request_id)                   -> bytes or -errno
//   hds_aio_drain(h)                              -> #completed
//   hds_aio_destroy(h)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Request {
  int64_t id;
  bool is_write;
  std::string path;
  char* buf;
  int64_t nbytes;
  int64_t offset;
  int64_t result = 0;
  bool done = false;
};

struct Pool {
  std::vector<std::thread> workers;
  std::deque<std::shared_ptr<Request>> queue;
  std::map<int64_t, std::shared_ptr<Request>> inflight;
  std::mutex mu;
  std::condition_variable cv_work, cv_done;
  std::atomic<int64_t> next_id{1};
  bool stopping = false;

  explicit Pool(int num_threads) {
    for (int i = 0; i < num_threads; ++i)
      workers.emplace_back([this] { run(); });
  }

  ~Pool() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stopping = true;
    }
    cv_work.notify_all();
    for (auto& t : workers) t.join();
  }

  static int64_t do_io(Request& r) {
    int flags = r.is_write ? (O_WRONLY | O_CREAT) : O_RDONLY;
    int fd = ::open(r.path.c_str(), flags, 0644);
    if (fd < 0) return -errno;
    int64_t total = 0;
    while (total < r.nbytes) {
      ssize_t n = r.is_write
          ? ::pwrite(fd, r.buf + total, r.nbytes - total, r.offset + total)
          : ::pread(fd, r.buf + total, r.nbytes - total, r.offset + total);
      if (n < 0) {
        int64_t err = -errno;
        ::close(fd);
        return err;
      }
      if (n == 0) break;  // EOF on read
      total += n;
    }
    if (r.is_write && ::fsync(fd) != 0) {
      int64_t err = -errno;
      ::close(fd);
      return err;
    }
    ::close(fd);
    return total;
  }

  void run() {
    for (;;) {
      std::shared_ptr<Request> req;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_work.wait(lk, [this] { return stopping || !queue.empty(); });
        if (stopping && queue.empty()) return;
        req = queue.front();
        queue.pop_front();
      }
      int64_t result = do_io(*req);
      {
        std::lock_guard<std::mutex> lk(mu);
        req->result = result;
        req->done = true;
      }
      cv_done.notify_all();
    }
  }

  int64_t submit(bool is_write, const char* path, char* buf, int64_t n,
                 int64_t offset) {
    auto req = std::make_shared<Request>();
    req->id = next_id.fetch_add(1);
    req->is_write = is_write;
    req->path = path;
    req->buf = buf;
    req->nbytes = n;
    req->offset = offset;
    {
      std::lock_guard<std::mutex> lk(mu);
      queue.push_back(req);
      inflight[req->id] = req;
    }
    cv_work.notify_one();
    return req->id;
  }

  int64_t wait(int64_t id) {
    std::unique_lock<std::mutex> lk(mu);
    auto it = inflight.find(id);
    if (it == inflight.end()) return -EINVAL;
    auto req = it->second;
    cv_done.wait(lk, [&] { return req->done; });
    inflight.erase(id);
    return req->result;
  }

  int64_t drain() {
    std::unique_lock<std::mutex> lk(mu);
    cv_done.wait(lk, [this] {
      for (auto& kv : inflight)
        if (!kv.second->done) return false;
      return true;
    });
    int64_t n = static_cast<int64_t>(inflight.size());
    inflight.clear();
    return n;
  }
};

std::mutex g_mu;
std::map<int64_t, std::unique_ptr<Pool>> g_pools;
int64_t g_next_handle = 1;

}  // namespace

extern "C" {

int64_t hds_aio_create(int num_threads, int /*queue_depth*/) {
  std::lock_guard<std::mutex> lk(g_mu);
  int64_t h = g_next_handle++;
  g_pools[h] = std::make_unique<Pool>(num_threads > 0 ? num_threads : 4);
  return h;
}

int64_t hds_aio_submit_read(int64_t h, const char* path, void* buf,
                            int64_t nbytes, int64_t offset) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_pools.find(h);
  if (it == g_pools.end()) return -EINVAL;
  return it->second->submit(false, path, static_cast<char*>(buf), nbytes,
                            offset);
}

int64_t hds_aio_submit_write(int64_t h, const char* path, void* buf,
                             int64_t nbytes, int64_t offset) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_pools.find(h);
  if (it == g_pools.end()) return -EINVAL;
  return it->second->submit(true, path, static_cast<char*>(buf), nbytes,
                            offset);
}

int64_t hds_aio_wait(int64_t h, int64_t request_id) {
  Pool* pool;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_pools.find(h);
    if (it == g_pools.end()) return -EINVAL;
    pool = it->second.get();
  }
  return pool->wait(request_id);
}

int64_t hds_aio_drain(int64_t h) {
  Pool* pool;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_pools.find(h);
    if (it == g_pools.end()) return -EINVAL;
    pool = it->second.get();
  }
  return pool->drain();
}

int hds_aio_destroy(int64_t h) {
  std::lock_guard<std::mutex> lk(g_mu);
  return g_pools.erase(h) ? 0 : -EINVAL;
}

}  // extern "C"
