"""Long-context training with Ulysses sequence parallelism (reference:
``deepspeed/sequence/layer.py`` DistributedAttention + the
deepspeed-ulysses blog recipe) and the ring-attention alternative.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/long_context_ulysses.py

Trains a Llama block stack with the sequence dimension sharded over a
4-way ``seq`` mesh axis (x 2-way data): attention runs through the
head<->sequence all-to-all pair, so each device holds 1/4 of every
sequence while attention still sees full context. Then checks the
ring-attention path (ppermute ring over the same axis — the
capability DeepSpeed points at FPDT for) against dense attention.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import hcache_deepspeed_tpu as hds  # noqa: E402
from hcache_deepspeed_tpu.models.llama import (LlamaForCausalLM,  # noqa: E402
                                               llama_tiny)
from hcache_deepspeed_tpu.ops.flash_attention import (  # noqa: E402
    reference_attention)
from hcache_deepspeed_tpu.parallel import topology as topo_mod  # noqa: E402
from hcache_deepspeed_tpu.sequence.layer import (  # noqa: E402
    make_ulysses_attention_fn)
from hcache_deepspeed_tpu.sequence.ring import ring_attention  # noqa: E402


def main():
    topo = topo_mod.initialize_topology(
        topo_mod.TopologySpec(seq=4, data=2))
    print("mesh:", topo.mesh)

    # --- Ulysses: engine training with the seq axis live
    cfg = llama_tiny(n_head=4, n_kv_head=4, max_positions=256)
    model = LlamaForCausalLM(
        cfg, attention_fn=make_ulysses_attention_fn(topology=topo))
    rng = np.random.default_rng(0)
    seq_len = 256   # 4x a single device's 64-token share
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (4, seq_len),
                                       dtype=np.int32)}
    engine, _, _, _ = hds.initialize(
        model=model,
        config={
            "train_batch_size": 4,
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}},
            "zero_optimization": {"stage": 2, "min_shard_size": 1},
        },
        example_batch=batch, topology=topo)
    losses = [float(engine.train_batch(batch=batch)) for _ in range(6)]
    print("ulysses seq=4 losses:", [round(l, 4) for l in losses])
    assert losses[-1] < losses[0]

    # --- Ring attention over the same axis: ppermute ring, full-context
    # math, O(T/sp) resident keys — parity vs dense attention
    B, T, H, D = 2, 128, 4, 32
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    dense = reference_attention(q, k, v, causal=True)

    ring = jax.jit(lambda *a: ring_attention(
        *a, causal=True, topology=topo))(q, k, v)
    err = float(jnp.max(jnp.abs(ring - dense)))
    print(f"ring-attention max |err| vs dense: {err:.2e}")
    assert err < 2e-4
    print("long-context paths verified")


if __name__ == "__main__":
    main()
