"""ZeRO-3 Llama training on a device mesh (the BASELINE north-star
config shape, scaled down so it also runs on a virtual CPU mesh).

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_zero3_llama.py

On a pod, drop the env vars and raise the model/config sizes.
"""

import numpy as np

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import hcache_deepspeed_tpu as hds
from hcache_deepspeed_tpu.models.llama import (LlamaForCausalLM,
                                               llama_tiny)
from hcache_deepspeed_tpu.parallel import topology as topo_mod


def main():
    import jax
    n = len(jax.devices())
    tensor = 2 if n % 2 == 0 else 1
    topo = topo_mod.initialize_topology(
        topo_mod.TopologySpec(data=n // tensor, tensor=tensor))

    cfg = llama_tiny(max_positions=256)   # swap for llama2_7b() at scale
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    dp = topo.dp_world_size()
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (2 * dp, 128),
                                       dtype=np.int32)}

    engine, _, _, _ = hds.initialize(
        model=model, example_batch=batch, topology=topo,
        config={
            "train_batch_size": 2 * dp,
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 3e-4}},
            "bf16": {"enabled": True},
            "gradient_clipping": 1.0,
            "zero_optimization": {"stage": 3, "min_shard_size": 1},
        })

    for step in range(10):
        loss = float(engine.train_batch(batch=batch))
        print(f"step {step}: loss {loss:.4f}")
    engine.save_checkpoint("/tmp/hds_example_ckpt")
    print("checkpoint saved; resume with engine.load_checkpoint(...)")


if __name__ == "__main__":
    main()
