"""RLHF-style loop on the hybrid engine (reference:
``deepspeed/runtime/hybrid_engine.py`` — the DeepSpeed-Chat train ↔
generate flip). Algorithm: rejection-sampling fine-tuning (RAFT /
best-of-N + SFT, the Llama-2-style RLHF alternative) — the same
rollout/update mechanics as PPO with a far smaller example surface.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/rlhf_raft_loop.py

Per iteration: sample N continuations per prompt fully on device
(``generate_fused`` at temperature 1 with per-token behavior-policy
logprobs — the PPO rollout primitive), score them with a toy reward,
then SFT on each prompt's best continuation (labels ``-100`` on the
prompt so only chosen actions train). Parameter refresh back into the
serving engine is one resharding copy; the mean reward climbs.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import hcache_deepspeed_tpu as hds  # noqa: E402
from hcache_deepspeed_tpu.inference.config import (  # noqa: E402
    RaggedInferenceEngineConfig)
from hcache_deepspeed_tpu.models.llama import (LlamaForCausalLM,  # noqa: E402
                                               llama_tiny)
from hcache_deepspeed_tpu.runtime.hybrid_engine import HybridEngine  # noqa: E402

PROMPT_LEN, MAX_NEW, N_SAMPLES = 8, 8, 4
SEQ = PROMPT_LEN + MAX_NEW
GOOD_BELOW = 32   # an eighth of the vocab counts as "good"


def reward(continuation):
    """Toy graded reward: fraction of generated tokens in the "good"
    region — dense enough that best-of-N finds signal at random init
    (a needle-token reward starts at ~1/vocab and RAFT's selection has
    nothing to amplify)."""
    c = np.asarray(continuation)
    return float((c < GOOD_BELOW).mean()) if c.size else 0.0


def main():
    mcfg = llama_tiny(max_positions=SEQ * 2)
    rng = np.random.default_rng(0)
    train_batch = {
        "input_ids": rng.integers(0, mcfg.vocab_size, (8, SEQ),
                                  dtype=np.int32),
        "labels": np.full((8, SEQ), -100, np.int32),
    }
    engine, _, _, _ = hds.initialize(
        model=LlamaForCausalLM(mcfg),
        config={
            "train_batch_size": 8,
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 2, "min_shard_size": 1},
        },
        example_batch=train_batch)
    hybrid = HybridEngine(
        engine, mcfg,
        inference_config=RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 32,
                           "max_ragged_batch_size": 1024,
                           "max_ragged_sequence_count": 32,
                           "max_context": SEQ * 2},
            kv_cache={"block_size": 16, "num_blocks": 128,
                      "cache_dtype": "float32"}))

    prompts = [rng.integers(0, mcfg.vocab_size, (PROMPT_LEN,)).tolist()
               for _ in range(8)]
    curve = []
    for it in range(4):
        # --- rollout: N samples per prompt, one device dispatch per
        # wave, with behavior-policy logprobs (the PPO primitive)
        flat = [p for p in prompts for _ in range(N_SAMPLES)]
        outs, _, logps = hybrid.generate_fused(
            flat, max_new_tokens=MAX_NEW, temperature=1.0,
            return_logprobs=True)
        rewards = [reward(o) for o in outs]
        curve.append(float(np.mean(rewards)))

        # --- selection: best-of-N per prompt
        ids, labels = [], []
        for i, p in enumerate(prompts):
            grp = range(i * N_SAMPLES, (i + 1) * N_SAMPLES)
            best = max(grp, key=lambda j: (rewards[j],
                                           float(np.sum(logps[j]))))
            # no eos_token_id -> continuations are exactly MAX_NEW long
            cont = list(outs[best])
            ids.append(p + cont)
            labels.append([-100] * PROMPT_LEN + cont)

        # --- update: SFT on the winners (prompt masked out), then the
        # hybrid refreshes serving params in one resharding copy
        sft = {"input_ids": np.asarray(ids, np.int32),
               "labels": np.asarray(labels, np.int32)}
        for _ in range(8):
            loss = float(hybrid.train_batch(batch=sft))
        print(f"iter {it}: mean reward {curve[-1]:.3f}  "
              f"sft loss {loss:.3f}")

    final = [reward(o) for o in hybrid.generate_fused(
        [p for p in prompts for _ in range(N_SAMPLES)],
        max_new_tokens=MAX_NEW, temperature=1.0)[0]]
    print(f"final mean reward {np.mean(final):.3f} "
          f"(started {curve[0]:.3f})")
    assert np.mean(final) > curve[0] + 0.1, (curve, np.mean(final))
    print("policy improved via rollout -> select -> SFT -> refresh")


if __name__ == "__main__":
    main()
