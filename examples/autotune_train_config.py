"""Model-based autotuning over real engine candidates (reference:
``deepspeed/autotuning`` — OOM-prune with a cost model, time only the
candidates the model selects, emit ``ds_config_optimal.json``).

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/autotune_train_config.py

The space crosses micro-batch x gradient-accumulation x ZeRO stage x
remat at a fixed global batch. Each candidate builds a real engine;
``aot_estimate`` AOT-compiles its fused train step (no execution) for
the OOM prune + roofline prior, then the tuner measures only the
model-selected half of the space with real steps.
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

import hcache_deepspeed_tpu as hds  # noqa: E402
from hcache_deepspeed_tpu.autotuning import (ModelBasedAutotuner,  # noqa: E402
                                             aot_estimate)
from hcache_deepspeed_tpu.models.gpt2 import (GPT2LMHeadModel,  # noqa: E402
                                              gpt2_tiny)
from hcache_deepspeed_tpu.parallel import topology as topo_mod  # noqa: E402

GLOBAL_BATCH = 32
SEQ = 64


class EngineRunner:
    """build_fn product: a real HDSEngine behind the tuner's
    ``estimate()`` / ``step()`` contract."""

    def __init__(self, cand):
        topo_mod.reset_topology()
        cfg = gpt2_tiny()
        rng = np.random.default_rng(0)
        self.batch = {"input_ids": rng.integers(
            0, cfg.vocab_size, (GLOBAL_BATCH, SEQ), dtype=np.int32)}
        self.engine, _, _, _ = hds.initialize(
            model=GPT2LMHeadModel(
                type(cfg)(**{**cfg.__dict__, "remat": cand["remat"]})),
            config={
                "train_batch_size": GLOBAL_BATCH,
                "train_micro_batch_size_per_gpu": cand["micro_batch"],
                "gradient_accumulation_steps": cand["gas"],
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": cand["zero_stage"],
                                      "min_shard_size": 1},
                "bf16": {"enabled": True},
            },
            example_batch=self.batch)

    def estimate(self):
        e = self.engine
        shaped = e._shard_batch(
            jax.tree.map(lambda x: np.asarray(x).reshape(
                (e.gradient_accumulation_steps, -1)
                + np.asarray(x).shape[1:]), self.batch),
            extra_leading=True)
        import jax.numpy as jnp
        return aot_estimate(e._fused_train_batch, e.state, shaped,
                            jnp.float32(1e-3), jax.random.PRNGKey(0))

    def step(self):
        float(self.engine.train_batch(batch=self.batch))

    def close(self):
        # the tuner builds one engine per candidate back-to-back; drop
        # this trial's device buffers before the next trial's engine
        # allocates (overlapping engine lifetimes is the OOM mode
        # benchmark._model_params documents)
        state, self.engine = getattr(self.engine, "state", None), None
        if state is not None:
            for leaf in jax.tree.leaves(state):
                if hasattr(leaf, "delete"):
                    leaf.delete()


def main():
    space = [
        {"micro_batch": mb, "gas": GLOBAL_BATCH // (mb * 8),
         "zero_stage": z, "remat": r}
        for mb in (1, 2, 4)
        for z in (0, 2)
        for r in (False, True)
        if GLOBAL_BATCH % (mb * 8) == 0 and GLOBAL_BATCH // (mb * 8) >= 1
    ]
    print(f"space: {len(space)} candidates")
    out = tempfile.mkdtemp(prefix="hds_autotune_")
    tuner = ModelBasedAutotuner(
        EngineRunner, space,
        # generous host budget: the prune stage is demonstrated by the
        # estimate numbers in the ledger, not by rejecting candidates
        hbm_budget_bytes=64 << 30,
        init_num=2, warmup_steps=1, measure_steps=2,
        state_path=os.path.join(out, "state.json"))
    best = tuner.tune()
    tuner.write_results(out)
    print(f"measured {len(tuner.results)} of {len(space)} candidates")
    print("best:", best.config, f"{best.throughput:.2f} steps/s")
    print("artifacts:", sorted(os.listdir(out)))


if __name__ == "__main__":
    main()
