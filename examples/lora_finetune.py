"""QLoRA fine-tuning (reference: deepspeed/linear/ OptimizedLinear).

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/lora_finetune.py

The base model is frozen (here int8-quantized, QLoRA-style) and sharded
by the ZeRO stage; the optimizer only ever sees the tiny adapter
factors. ``save_16bit_model`` exports the merged full-weight model.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import hcache_deepspeed_tpu as hds
from hcache_deepspeed_tpu.models.llama import LlamaForCausalLM, llama_tiny


def main():
    import jax

    cfg = llama_tiny(max_positions=256)   # swap for a real checkpoint's
    # config + init_params=convert_hf_state_dict(...) at scale
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (8, 128),
                                       dtype=np.int32)}

    engine, _, _, _ = hds.initialize(
        model=model, example_batch=batch,
        config={
            "train_batch_size": 8,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3},
            "lora": {
                "enabled": True,
                "lora_r": 8,
                "lora_alpha": 16.0,
                # llama projection names are the default target_mods
                "quantization": {"enabled": True, "q_bits": 8,
                                 "group_size": 128},
            },
            "steps_per_print": 5,
        })

    n_trainable = sum(x.size for x in jax.tree.leaves(
        engine.state["params"]))
    n_frozen = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(
        engine.state["frozen"]))
    print(f"trainable adapter params: {n_trainable:,} "
          f"(base: {n_frozen:,} frozen)")

    for step in range(10):
        loss = engine.train_batch(batch=batch)
        if step % 5 == 0:
            print(f"step {step}: loss {float(loss):.4f}")

    engine.save_checkpoint("/tmp/hds_lora_ckpt")       # adapters only
    engine.save_16bit_model("/tmp/hds_lora_export")    # merged weights
    print("saved adapter checkpoint and merged export")


if __name__ == "__main__":
    main()
