"""Pretrain GPT-2 from a native indexed token dataset.

Builds a tiny corpus on the fly, then streams shuffled LM batches from
the C++ prefetching loader into the fused train step. Run on CPU with:

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/pretrain_indexed_gpt2.py
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    # honor the env var even when jax was preloaded before it could be
    # read (site customizations) — the conftest trick
    jax.config.update("jax_platforms", "cpu")

import hcache_deepspeed_tpu as hds  # noqa: E402
from hcache_deepspeed_tpu.models.gpt2 import (GPT2LMHeadModel,  # noqa: E402
                                              gpt2_tiny)
from hcache_deepspeed_tpu.runtime.data import (NativeTokenLoader,  # noqa: E402
                                               write_indexed_dataset)


def main():
    mcfg = gpt2_tiny()
    rng = np.random.default_rng(0)
    corpus_dir = tempfile.mkdtemp()
    prefix = write_indexed_dataset(
        os.path.join(corpus_dir, "corpus"),
        [rng.integers(0, mcfg.vocab_size, (int(rng.integers(32, 256)),))
         for _ in range(64)])

    loader = NativeTokenLoader(prefix, seq_len=32, batch_size=8, seed=1)
    engine, _, _, _ = hds.initialize(
        model=GPT2LMHeadModel(mcfg),
        example_batch=next(loader),
        config={
            "train_batch_size": 8,
            "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
            "zero_optimization": {"stage": 2, "min_shard_size": 1},
            "steps_per_print": 20,
        })
    for step in range(40):
        loss = float(engine.train_batch(batch=next(loader)))
        if step % 10 == 0:
            print(f"step {step:3d}  epoch {loader.epoch}  "
                  f"loss {loss:.4f}")
    loader.close()
    print("done; final loss", round(loss, 4))


if __name__ == "__main__":
    main()
