"""Serve a HF checkpoint (reference: build_hf_engine, engine_factory.py:69).

    JAX_PLATFORMS=cpu python examples/serve_hf_checkpoint.py

A transformers model's state_dict converts straight into the paged
serving engine's param tree; generation is greedy-decode-identical to
the torch model. At scale, point ``convert_hf_state_dict`` at a
``.safetensors`` file instead of an in-memory model.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import torch
    import transformers

    from hcache_deepspeed_tpu.checkpoint.hf_loader import \
        convert_hf_state_dict
    from hcache_deepspeed_tpu.inference import (RaggedInferenceEngineConfig,
                                                build_hf_engine)

    # stand-in for e.g. LlamaForCausalLM.from_pretrained(...)
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()

    params = jax.tree.map(lambda x: np.asarray(x, np.float32),
                          convert_hf_state_dict(hf_model, "llama"))
    engine = build_hf_engine(
        {**hf_model.config.to_dict(), "torch_dtype": "float32"}, params,
        engine_config=RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 8, "max_context": 128},
            kv_cache={"block_size": 16, "num_blocks": 64,
                      "cache_dtype": "float32"}))

    prompt = [3, 17, 250, 99, 1]
    out = engine.generate([prompt], max_new_tokens=16)
    print("prompt:", prompt)
    print("generated:", list(out[0]))


if __name__ == "__main__":
    main()
