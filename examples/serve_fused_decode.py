"""Fused on-device decoding: the whole decode stretch compiles into ONE
device program (lax.scan with the sampled token fed back on device), so
the host syncs once per generation instead of once per token — the
TPU-native serving shape. Demonstrates greedy + nucleus sampling and
per-token logprobs (RLHF consumers), and that the returned latents keep
the sequence HCache-restorable.

    JAX_PLATFORMS=cpu python examples/serve_fused_decode.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
from hcache_deepspeed_tpu.inference import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig)
from hcache_deepspeed_tpu.models.llama import LlamaForCausalLM, llama_tiny


def main():
    cfg = llama_tiny(max_positions=256)
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": np.zeros((1, 8), np.int32)},
                        train=False)["params"]
    engine = InferenceEngineV2(
        cfg, params,
        config=RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 8,
                           "max_context": 256},
            kv_cache={"block_size": 16, "num_blocks": 64,
                      "cache_dtype": "float32"}))

    rng = np.random.default_rng(0)
    prompts = [list(rng.integers(0, cfg.vocab_size, (n,)))
               for n in (12, 7)]

    # greedy, with per-token raw-model logprobs
    outs, latents, logprobs = engine.generate_fused(
        prompts, max_new_tokens=12, return_logprobs=True)
    for i, (o, lp) in enumerate(zip(outs, logprobs)):
        print(f"seq {i}: greedy tokens {o}")
        print(f"        logprobs {np.round(lp, 3).tolist()}")

    # nucleus sampling — temperature/top_p are traced, so different
    # values reuse the same compiled program
    for temp in (0.7, 1.2):
        sampled, _ = engine.generate_fused(prompts, max_new_tokens=12,
                                           temperature=temp, top_p=0.9,
                                           seed=42)
        print(f"temp {temp}: {sampled[0]}")

    # the returned latents cover prompt + fed tokens: a flushed sequence
    # restores without a prefill recompute (HCache), then keeps decoding
    cached = prompts[0] + outs[0][:-1]
    engine.restore_kv([99], [cached], [latents[0]])
    cont, _ = engine.put([99], [[outs[0][-1]]])
    print("post-restore next-token logit argmax:",
          int(np.argmax(cont[0])))


if __name__ == "__main__":
    main()
