"""Structured compression: config-driven prune -> train -> export
(reference: ``deepspeed/compression`` — the ``init_compression`` /
``redundancy_clean`` user flow).

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/compress_prune_export.py

Row-prunes the MLP up-projections of GPT-2-tiny to half width with
learnable topk scores while weight-quantizing attention, trains a few
steps, then exports a dimension-reduced model that reproduces the
masked model's loss.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import hcache_deepspeed_tpu as hds  # noqa: E402
from hcache_deepspeed_tpu.compression import redundancy_clean  # noqa: E402
from hcache_deepspeed_tpu.models.gpt2 import (GPT2LMHeadModel,  # noqa: E402
                                              gpt2_tiny)


def main():
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 256, (8, 32), np.int32)}
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "compression_training": {
            "row_pruning": {
                "shared_parameters": {"enabled": True,
                                      "schedule_offset": 2,
                                      "method": "l1"},
                "different_groups": {"rp1": {
                    "params": {"dense_ratio": 0.5},
                    "modules": [r"mlp/c_fc"],
                    "related_modules": [[r"mlp/c_proj"]]}}},
            "weight_quantization": {
                "shared_parameters": {"enabled": True,
                                      "schedule_offset": 0},
                "different_groups": {"wq1": {
                    "params": {"start_bits": 12, "target_bits": 8,
                               "quantization_period": 2},
                    "modules": [r"attn/c_attn"]}}},
        },
    }
    engine, _, _, _ = hds.initialize(model=GPT2LMHeadModel(gpt2_tiny()),
                                     config=config, example_batch=batch)
    for step in range(8):
        loss = float(engine.train_batch(batch=batch))
        print(f"step {step}: loss {loss:.4f}")

    host = jax.device_get(engine.state["params"])
    fixed, dims = redundancy_clean(host, config, engine._structured)
    print("dimension-reduced exports:", {k: v for k, v in dims.items()
                                         if "c_fc" in k})
    small = GPT2LMHeadModel(gpt2_tiny(n_inner=128))
    out = small.apply({"params": jax.tree.map(jnp.asarray, fixed)}, batch)
    loss = float(out[0] if isinstance(out, tuple) else out)
    print(f"exported n_inner=128 model loss: {loss:.4f}")


if __name__ == "__main__":
    main()
