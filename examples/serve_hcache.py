"""Ragged serving + the HCache restore flow (the fork's flagship):
prefill returns per-layer latents; after evicting a sequence's KV, the
cache is rebuilt from latents by replaying ONLY the QKV projections —
far cheaper than a full prefill.

    JAX_PLATFORMS=cpu python examples/serve_hcache.py
"""

import numpy as np

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import jax
from hcache_deepspeed_tpu.inference import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig)
from hcache_deepspeed_tpu.models.llama import LlamaForCausalLM, llama_tiny


def main():
    cfg = llama_tiny(max_positions=256)
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": np.zeros((1, 8), np.int32)},
                        train=False)["params"]

    engine = InferenceEngineV2(
        cfg, params,
        config=RaggedInferenceEngineConfig(
            state_manager={"max_tracked_sequences": 16,
                           "max_context": 256},
            kv_cache={"block_size": 32, "num_blocks": 64}))

    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, (48,)).tolist()

    # 1) normal prefill: logits for the next token + HCache latents
    logits, latents = engine.put([7], [prompt])
    next_tok = int(np.argmax(logits[0]))
    print(f"prefill done; latents per layer: {latents[0].shape}")

    # 2) sequence evicted (e.g. conversation went idle)
    engine.flush(7)

    # 3) conversation resumes: restore the KV cache from latents
    engine.restore_kv([7], [prompt], [latents[0]])
    dec, _ = engine.put([7], [[next_tok]])
    print(f"restored + decoded; argmax {int(np.argmax(dec[0]))}")

    # 4) continuous-batching generation across many prompts
    prompts = [rng.integers(0, cfg.vocab_size, (n,)).tolist()
               for n in (12, 30, 7, 21)]
    outs = engine.generate(prompts, max_new_tokens=16)
    print("generated:", [len(o) for o in outs], "tokens per prompt")


if __name__ == "__main__":
    main()
