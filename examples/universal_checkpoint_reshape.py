"""Universal checkpoint: train at one topology, resume at another
(reference: ``deepspeed/checkpoint/ds_to_universal.py`` + the
``--universal-checkpoint`` engine flag; here reshape-on-load is the
default save format — param-name-keyed fp32 fragments reshard to
whatever mesh the restoring engine runs).

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/universal_checkpoint_reshape.py

Trains ZeRO-3 data-parallel over 8 devices, checkpoints, then resumes
on a different mesh (4-way data x 2-way tensor) and keeps training —
the dp/tp-resize flow the reference needs an offline conversion step
for.
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import hcache_deepspeed_tpu as hds  # noqa: E402
from hcache_deepspeed_tpu.models.gpt2 import (GPT2LMHeadModel,  # noqa: E402
                                              gpt2_tiny)
from hcache_deepspeed_tpu.parallel import topology as topo_mod  # noqa: E402


def make_engine(cfg, data, tensor, batch):
    topo = topo_mod.initialize_topology(
        topo_mod.TopologySpec(data=data, tensor=tensor))
    engine, _, _, _ = hds.initialize(
        model=GPT2LMHeadModel(cfg),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
            "zero_optimization": {"stage": 3, "min_shard_size": 1},
            "bf16": {"enabled": True},
        },
        example_batch=batch, topology=topo)
    return engine


def main():
    cfg = gpt2_tiny()
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (16, 16),
                                       dtype=np.int32)}
    ckpt = tempfile.mkdtemp(prefix="hds_universal_")

    # --- phase 1: ZeRO-3 over a pure data mesh (dp=8)
    e1 = make_engine(cfg, data=8, tensor=1, batch=batch)
    for step in range(4):
        loss = float(e1.train_batch(batch=batch))
        print(f"dp=8    step {step}: loss {loss:.4f}")
    e1.save_checkpoint(ckpt, tag="reshape")

    # --- phase 2: resume on a RESHAPED mesh (dp=4 x tp=2)
    topo_mod.reset_topology()
    e2 = make_engine(cfg, data=4, tensor=2, batch=batch)
    e2.load_checkpoint(ckpt, tag="reshape")
    for step in range(4, 8):
        loss = float(e2.train_batch(batch=batch))
        print(f"dp4xtp2 step {step}: loss {loss:.4f}")
    print("resumed across topologies; final loss", f"{loss:.4f}")


if __name__ == "__main__":
    main()
