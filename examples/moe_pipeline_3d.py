"""3D-parallel training sampler: (a) pipeline-parallel GPT-2 with the
compiled 1F1B executor, (b) dropless Mixtral over data x expert x tensor.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/moe_pipeline_3d.py
"""

import numpy as np

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import hcache_deepspeed_tpu as hds
from hcache_deepspeed_tpu.parallel import topology as topo_mod


def pipeline_example():
    from hcache_deepspeed_tpu.models.gpt2 import (gpt2_pipeline_layers,
                                                  gpt2_tiny)
    from hcache_deepspeed_tpu.runtime.pipe import PipelineModule

    topo = topo_mod.initialize_topology(
        topo_mod.TopologySpec(pipe=2, data=4))
    cfg = gpt2_tiny(n_layer=4)
    layers, loss_fn = gpt2_pipeline_layers(cfg)
    module = PipelineModule(layers, loss_fn, topology=topo)

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (16, 32),
                                       dtype=np.int32)}
    engine, _, _, _ = hds.initialize(
        model=module, topology=topo, example_batch=batch,
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 4,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 1, "min_shard_size": 1}})
    for step in range(4):
        print(f"pipe step {step}: "
              f"loss {float(engine.train_batch(batch=batch)):.4f}")
    topo_mod.reset_topology()


def moe_example():
    from hcache_deepspeed_tpu.models.mixtral import (MixtralForCausalLM,
                                                     mixtral_tiny)

    topo = topo_mod.initialize_topology(
        topo_mod.TopologySpec(data=2, expert=2, tensor=2))
    cfg = mixtral_tiny(dropless=True, use_flash=False)
    rng = np.random.default_rng(1)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (8, 32),
                                       dtype=np.int32)}
    engine, _, _, _ = hds.initialize(
        model=MixtralForCausalLM(cfg), topology=topo,
        example_batch=batch,
        config={"train_batch_size": 8,
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 2, "min_shard_size": 1}})
    for step in range(4):
        print(f"moe step {step}: "
              f"loss {float(engine.train_batch(batch=batch)):.4f}")
    topo_mod.reset_topology()


if __name__ == "__main__":
    pipeline_example()
    moe_example()
