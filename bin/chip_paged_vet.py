#!/usr/bin/env python3
"""On-chip vet for the head-tiled paged-attention kernel: Mosaic
lowering, parity vs the dense-gather oracle, and timing vs the
single-head grid (head_tile=1 reproduces the old kernel's schedule).

Timing method: scan-stretch SLOPE — (t_256 - t_32)/224, best of 3 each.
A single timed dispatch through the axon relay carries a variable
25-70 ms round-trip cost; at 32 iterations that reads as ~1-2 ms/iter
of phantom kernel time (this contaminated the first version of this
vet AND hds_decode_diag's floor phases).

Emits JSON lines; run inside a chip session:
    python bin/chip_paged_vet.py
"""
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from hcache_deepspeed_tpu import default_compile_cache_dir
    jax.config.update("jax_compilation_cache_dir",
                      default_compile_cache_dir())
    from hcache_deepspeed_tpu.ops.paged_attention import (
        pallas_paged_attention, reference_paged_attention)

    def emit(row):
        print(json.dumps(row), flush=True)

    # 1B decode shape: 8 lanes, 32 heads, D=64, context 512
    rng = np.random.default_rng(0)
    B, T, Hq, KV, D, BS, NBLK, NB = 8, 1, 32, 32, 64, 64, 72, 8
    q = jnp.asarray(rng.standard_normal((B, T, Hq, D)), jnp.bfloat16)
    kp = jnp.asarray(rng.standard_normal((KV, NBLK * BS, D)),
                     jnp.bfloat16)
    vp = jnp.asarray(rng.standard_normal((KV, NBLK * BS, D)),
                     jnp.bfloat16)
    tables = rng.permutation(NBLK)[:B * NB].reshape(B, NB).astype(np.int32)
    start = jnp.asarray([511, 300, 128, 64, 511, 17, 480, 2], jnp.int32)
    kvl = start + 1

    ref = np.asarray(reference_paged_attention(
        q, kp, vp, tables, start, kvl, BS), np.float32)

    import functools

    def slope_ms(stretch, *operands, reps=5):
        """Per-iteration device time from interleaved 32/256-length
        stretch samples: median(t_256) - median(t_32) over 224 — the
        relay's variable fixed round trip swamps any single /n reading.
        Returns None (not a negative 'floor') when unresolvable."""
        for n in (32, 256):
            float(stretch(*operands, n))      # warm both programs
        lo, hi = [], []
        for _ in range(reps):
            for n, acc in ((32, lo), (256, hi)):
                t0 = time.perf_counter()
                float(stretch(*operands, n))
                acc.append(time.perf_counter() - t0)
        lo.sort()
        hi.sort()
        s = (hi[reps // 2] - lo[reps // 2]) / 224 * 1000
        return round(s, 4) if s > 0 else None

    for tile in (1, 8, 32):
        try:
            fn = jax.jit(lambda q, kp, vp, t=tile: pallas_paged_attention(
                q, kp, vp, tables, start, kvl, BS, interpret=False,
                head_tile=t))
            out = np.asarray(fn(q, kp, vp), np.float32)
            err = float(np.max(np.abs(out - ref)))

            # device time: N kernel iterations inside ONE dispatch (a
            # dispatch-per-call chain through the relay is enqueue-bound
            # and reads the same for every variant). Loop-carried q
            # perturbation keeps LICM from hoisting the kernel.
            @functools.partial(jax.jit, static_argnums=(3,))
            def stretch(q, kp, vp, n, t=tile):
                def step(c, _):
                    qq = q + (c * 1e-12).astype(q.dtype)
                    o = pallas_paged_attention(
                        qq, kp, vp, tables, start, kvl, BS,
                        interpret=False, head_tile=t)
                    return c + jnp.abs(o).sum().astype(jnp.float32), ()
                c, _ = jax.lax.scan(step, jnp.float32(0), None, length=n)
                return c

            ms = slope_ms(stretch, q, kp, vp)
            emit({"phase": "paged-vet", "head_tile": tile,
                  "max_abs_err": round(err, 5),
                  "ok": err < 0.05, "device_ms_per_iter": ms})
        except Exception as e:
            emit({"phase": "paged-vet", "head_tile": tile,
                  "error": str(e)[:300]})

    # ---- experimental: block-major pool layout [NBLK, KV, BS, D].
    # Hypothesis: the head-major pool makes every (head-tile, block) DMA
    # KVT strided 16 KB segments; block-major makes it ONE contiguous
    # KVT*BS*D segment — if this wins big, the engine layout flips.
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from hcache_deepspeed_tpu.ops.paged_attention import _NEG_INF

    def block_major_attention(q, kp_bm, vp_bm, tables, start, kvl, BS,
                              head_tile):
        B, T, Hq, D = q.shape
        NBLK, KV = kp_bm.shape[0], kp_bm.shape[1]
        G = Hq // KV
        NB = tables.shape[1]
        KVT = head_tile
        qg = q.reshape(B, T, KV, G, D).transpose(0, 2, 1, 3, 4).reshape(
            B, KV, T * G, D)
        TG = T * G
        TGp = max(8, -(-TG // 8) * 8)
        if TGp != TG:
            qg = jnp.pad(qg, ((0, 0), (0, 0), (0, TGp - TG), (0, 0)))

        def page_index(b, kh, nb, tables_ref, kvlen_ref, start_ref):
            last = jnp.maximum(kvlen_ref[b] - 1, 0) // BS
            return (tables_ref[b, jnp.minimum(nb, last)], kh, 0, 0)

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(B, KV // KVT, NB),
            in_specs=[
                pl.BlockSpec((1, KVT, TGp, D),
                             lambda b, kh, nb, *refs: (b, kh, 0, 0)),
                pl.BlockSpec((1, KVT, BS, D), page_index),
                pl.BlockSpec((1, KVT, BS, D), page_index),
            ],
            out_specs=pl.BlockSpec((1, KVT, TGp, D),
                                   lambda b, kh, nb, *refs: (b, kh, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((KVT, TGp, D), jnp.float32),
                pltpu.VMEM((KVT, TGp, 128), jnp.float32),
                pltpu.VMEM((KVT, TGp, 128), jnp.float32),
            ],
        )

        def kern(tables_ref, kvlen_ref, start_ref, q_ref, k_ref, v_ref,
                 o_ref, acc, m_s, l_s):
            # same online softmax as _kernel, block-major tile indexing
            b, nb = pl.program_id(0), pl.program_id(2)
            nblocks = pl.num_programs(2)

            @pl.when(nb == 0)
            def _init():
                acc[:] = jnp.zeros_like(acc)
                m_s[:] = jnp.full_like(m_s, _NEG_INF)
                l_s[:] = jnp.zeros_like(l_s)

            kvlen = kvlen_ref[b]
            st = start_ref[b]
            run = nb * BS < kvlen

            @pl.when(run)
            def _body():
                qq = q_ref[0]
                k = k_ref[0].astype(qq.dtype)
                s = jax.lax.dot_general(
                    qq, k, (((2,), (2,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32) / np.sqrt(D)
                rows = jax.lax.broadcasted_iota(jnp.int32, (TGp, BS), 0)
                cols = nb * BS + jax.lax.broadcasted_iota(
                    jnp.int32, (TGp, BS), 1)
                ok = (cols <= st + rows // G) & (cols < kvlen)
                s = jnp.where(ok[None], s, _NEG_INF)
                m_prev = m_s[:, :, :1]
                m_new = jnp.maximum(m_prev,
                                    jnp.max(s, axis=2, keepdims=True))
                p = jnp.exp(s - m_new)
                corr = jnp.exp(m_prev - m_new)
                l_s[:, :, :1] = corr * l_s[:, :, :1] + \
                    jnp.sum(p, axis=2, keepdims=True)
                m_s[:, :, :1] = m_new
                v = v_ref[0]
                acc[:] = acc[:] * corr + jax.lax.dot_general(
                    p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32)

            @pl.when(nb == nblocks - 1)
            def _out():
                l = l_s[:, :, :1]
                l = jnp.where(l == 0.0, 1.0, l)
                o_ref[0] = (acc[:] / l).astype(o_ref.dtype)

        out = pl.pallas_call(
            kern, grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((B, KV, TGp, D), q.dtype),
        )(tables, kvl, start, qg, kp_bm, vp_bm)
        out = out[:, :, :TG].reshape(B, KV, T, G, D).transpose(
            0, 2, 1, 3, 4)
        return out.reshape(B, T, Hq, D)

    kp_bm = jnp.asarray(np.asarray(kp).reshape(KV, NBLK, BS, D)
                        .transpose(1, 0, 2, 3))
    vp_bm = jnp.asarray(np.asarray(vp).reshape(KV, NBLK, BS, D)
                        .transpose(1, 0, 2, 3))
    for tile in (8, 32):
        try:
            fn = jax.jit(lambda q, kp_bm, vp_bm, t=tile:
                         block_major_attention(q, kp_bm, vp_bm, tables,
                                               start, kvl, BS, t))
            out = np.asarray(fn(q, kp_bm, vp_bm), np.float32)
            err = float(np.max(np.abs(out - ref)))

            @functools.partial(jax.jit, static_argnums=(3,))
            def stretch(q, kp_bm, vp_bm, n, t=tile):
                def step(c, _):
                    qq = q + (c * 1e-12).astype(q.dtype)
                    o = block_major_attention(qq, kp_bm, vp_bm, tables,
                                              start, kvl, BS, t)
                    return c + jnp.abs(o).sum().astype(jnp.float32), ()
                c, _ = jax.lax.scan(step, jnp.float32(0), None, length=n)
                return c

            ms = slope_ms(stretch, q, kp_bm, vp_bm)
            emit({"phase": "paged-vet-blockmajor", "head_tile": tile,
                  "max_abs_err": round(err, 5),
                  "ok": err < 0.05, "device_ms_per_iter": ms})
        except Exception as e:
            emit({"phase": "paged-vet-blockmajor", "head_tile": tile,
                  "error": str(e)[:300]})
    return 0


if __name__ == "__main__":
    sys.exit(main())
