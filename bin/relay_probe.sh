#!/usr/bin/env bash
# Round-5 relay watcher: probe the axon relay (incl. the remote-compile
# service) every ~4 min and append state to relay_state_r5.log.
# Consumers grep the log tail for "UP". The probe itself is
# bench._probe_relay — ONE implementation, so a probe fix (e.g. the
# cache-collision shape-space fix) applies to watcher and bench alike.
#
# ${PYTHON:-python3}: bare "python" is missing (or is python2) on some
# boxes — bench.py itself runs under sys.executable, so the watcher must
# not silently log DOWN() forever on a healthy relay just because the
# interpreter name differs. Probe-script stderr is logged ONCE (first
# failure) so "probe script failed" is distinguishable from "relay
# down".
set -u
cd "$(dirname "$0")/.."
PY="${PYTHON:-python3}"
DEADLINE=$(( $(date +%s) + ${1:-43200} ))
probe_err_logged=0
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  err=$(mktemp)
  state=$("$PY" -c "import bench; print(bench._probe_relay())" 2>"$err")
  if [ "$state" = "up" ]; then
    echo "UP $(date -u +%F_%H:%M:%S)"
  elif [ -z "$state" ]; then
    # the probe script itself failed (bad interpreter, import error):
    # a health signal about US, not about the relay
    echo "PROBE-FAILED $(date -u +%F_%H:%M:%S)"
    if [ "$probe_err_logged" -eq 0 ] && [ -s "$err" ]; then
      sed 's/^/  probe-stderr: /' "$err"
      probe_err_logged=1
    fi
  else
    echo "DOWN($state) $(date -u +%F_%H:%M:%S)"
  fi
  rm -f "$err"
  sleep 240
done
