#!/usr/bin/env bash
# Round-5 relay watcher: probe the axon relay (incl. the remote-compile
# service) every ~4 min and append state to relay_state_r5.log.
# Consumers grep the log tail for "UP". The probe itself is
# bench._probe_relay — ONE implementation, so a probe fix (e.g. the
# cache-collision shape-space fix) applies to watcher and bench alike.
set -u
cd "$(dirname "$0")/.."
DEADLINE=$(( $(date +%s) + ${1:-43200} ))
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  state=$(python -c "import bench; print(bench._probe_relay())" 2>/dev/null)
  if [ "$state" = "up" ]; then
    echo "UP $(date -u +%F_%H:%M:%S)"
  else
    echo "DOWN($state) $(date -u +%F_%H:%M:%S)"
  fi
  sleep 240
done
