#!/usr/bin/env bash
# Relay watcher (round-4 continuation): probe every ~5 min; on revival
# run the remaining measurement queue — HCache restore-vs-prefill at 1B
# (bf16 + fp8 latents) and 7B int8 fused-decode serving — then exit.
set -u -o pipefail   # `stage | tee` must report the stage's rc
cd "$(dirname "$0")/.."
DEADLINE=$(( $(date +%s) + ${1:-30000} ))

probe() {
  # fresh-shape compile: the compile service is a separate failure
  # domain from execution; a cached-program probe would report UP while
  # every new program hangs
  timeout 180 python -c "
import jax, jax.numpy as jnp, random
n = random.randrange(130, 510)
x = jnp.ones((n, 257))
assert jax.devices('tpu')
float(jax.jit(lambda a: (a @ a.T).sum())(x))" >/dev/null 2>&1
}

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if probe; then
    echo "relay (incl compile service) UP at $(date -u +%H:%M:%S)" >&2
    timeout 2400 python bin/hds_serve_bench --model 1b --restore \
      --prompt-len 128 --batches 1 4 | tee RESTORE_1B.jsonl
    echo "restore-1b rc=$?" >&2
    timeout 2400 python bin/hds_serve_bench --model 1b --restore \
      --latent-dtype float8_e4m3fn --prompt-len 128 --batches 1 4 \
      | tee RESTORE_1B_FP8.jsonl
    echo "restore-1b-fp8 rc=$?" >&2
    timeout 3300 python bin/hds_serve_bench --model 7b --quantize int8 \
      --max-context 512 --prompt-len 128 --decode-steps 8 --batches 1 \
      --prefill-chunk 64 --fused-decode | tee SERVE_7B_INT8_FUSED.jsonl
    echo "serve7b-int8-fused rc=$?" >&2
    echo "watch2 queue done" >&2
    exit 0
  fi
  sleep 280
done
echo "relay never revived before deadline" >&2
exit 3
