#!/usr/bin/env bash
# Relay watcher: probe every ~5 min; on the first revival run the
# leftover round-4 measurements (int8 7B serving, flash-tiling bench
# vets) once, then exit. Bounded lifetime so a dead relay doesn't hold
# a shell forever.
set -u
cd "$(dirname "$0")/.."
DEADLINE=$(( $(date +%s) + ${1:-12000} ))

probe() {
  timeout 75 python -c "import jax; d=jax.devices('tpu'); assert d" \
    >/dev/null 2>&1
}

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if probe; then
    echo "relay UP at $(date -u +%H:%M:%S); running leftover queue" >&2
    timeout 3300 python bin/hds_serve_bench --model 7b --quantize int8 \
      --max-context 512 --prompt-len 128 --decode-steps 8 --batches 1 \
      --prefill-chunk 64 | tee SERVE_7B_INT8.jsonl
    echo "int8 rc=$?" >&2
    bash bin/chip_session.sh vet
    echo "watch queue done" >&2
    exit 0
  fi
  sleep 280
done
echo "relay never revived before deadline" >&2
exit 3
