#!/usr/bin/env bash
# Round-4 continuation queue 3: decode-cost decomposition (why is fused
# decode ~40x above the HBM floor?), the marginal-cost HCache restore
# story (device replay vs link ship), and a fresh BENCH point.
set -u -o pipefail
cd "$(dirname "$0")/.."

probe() {
  timeout 180 python -c "
import jax, jax.numpy as jnp, random
n = random.randrange(130, 510)
x = jnp.ones((n, 257))
assert jax.devices('tpu')
float(jax.jit(lambda a: (a @ a.T).sum())(x))" >/dev/null 2>&1
}
probe || { echo "relay DOWN; aborting" >&2; exit 3; }
echo "relay UP at $(date -u +%H:%M:%S)" >&2

echo "=== decode-diag 1b" >&2
timeout 2400 python bin/hds_decode_diag --model 1b | tee DECODE_DIAG_1B.jsonl
echo "=== decode-diag rc=$?" >&2

echo "=== restore-marginal 1b (bf16)" >&2
timeout 2400 python bin/hds_serve_bench --model 1b --restore-marginal \
  --prompt-len 128 --batches 1 4 | tee RESTORE_1B_MARGINAL.jsonl
echo "=== restore-marginal rc=$?" >&2

echo "=== restore-marginal 1b (fp8 latents)" >&2
timeout 2400 python bin/hds_serve_bench --model 1b --restore-marginal \
  --latent-dtype float8_e4m3fn --prompt-len 128 --batches 1 4 \
  | tee RESTORE_1B_MARGINAL_FP8.jsonl
echo "=== restore-marginal-fp8 rc=$?" >&2

echo "=== fresh bench" >&2
timeout 3000 python bench.py | tee BENCH_FRESH.json
echo "=== bench rc=$?" >&2

echo "chip_queue5 done" >&2
