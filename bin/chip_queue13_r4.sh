#!/usr/bin/env bash
# Round-4 continuation queue 13: re-measure the 7B int8 decode story
# after the block_n-divisor fix (qkv + gate_up — 74% of weight bytes —
# had silently fallen back to dequant). Floors run FIRST in a pristine
# process (--floors-only: after a 7B engine the pool never reliably
# returns to a state that fits the 13.5 GB dense floor), then the
# engine stretch, then serving e2e, then the 1B diag (1B gate_up was
# also fallback-bound).
set -u -o pipefail
cd "$(dirname "$0")/.."

probe() {
  timeout 180 python -c "
import jax, jax.numpy as jnp, random
n = random.randrange(130, 510)
x = jnp.ones((n, 257))
assert jax.devices('tpu')
float(jax.jit(lambda a: (a @ a.T).sum())(x))" >/dev/null 2>&1
}
probe || { echo "relay DOWN; aborting" >&2; exit 3; }
echo "relay UP at $(date -u +%H:%M:%S)" >&2

echo "=== 7b int8 floors (fixed kernel, pristine process)" >&2
timeout 2400 python bin/hds_decode_diag --model 7b --quantize fused \
  --floors-only | tee DECODE_DIAG_7B_FLOORS_V2.jsonl
echo "=== floors rc=$?" >&2

echo "=== 7b fused stretch decomposition" >&2
timeout 2400 python bin/hds_decode_diag --model 7b --quantize fused \
  --stretch-only | tee DECODE_DIAG_7B_QFUSED_V2.jsonl
echo "=== stretch rc=$?" >&2

echo "=== serve 7b int8 fused decode e2e" >&2
timeout 3300 python bin/hds_serve_bench --model 7b --quantize fused \
  --max-context 512 --prompt-len 128 --decode-steps 8 --batches 1 \
  --prefill-chunk 64 --fused-decode | tee SERVE_7B_INT8_FUSED_V3.jsonl
echo "=== serve rc=$?" >&2

echo "=== 1b fused diag (gate_up no longer fallback)" >&2
timeout 2400 python bin/hds_decode_diag --model 1b --quantize fused \
  | tee DECODE_DIAG_1B_QFUSED_V2.jsonl
echo "=== diag-1b rc=$?" >&2
