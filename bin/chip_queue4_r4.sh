#!/usr/bin/env bash
# Round-4 continuation queue 2: consolidated train curve (plain +
# rpdots rows in one artifact), HCache restore-vs-prefill at 1B (the
# fork's headline capability, bf16 and fp8 latents), and 7B int8
# fused-decode serving (weight HBM traffic halved vs bf16).
set -u -o pipefail
cd "$(dirname "$0")/.."

probe() {
  timeout 75 python -c "import jax; d=jax.devices('tpu'); assert d, d" \
    >/dev/null 2>&1
}
probe || { echo "relay DOWN; aborting" >&2; exit 3; }
echo "relay UP" >&2

echo "=== train-curve (consolidated)" >&2
timeout 7200 python bin/hds_train_curve --out TRAIN_CURVE.json
echo "=== curve rc=$?" >&2

echo "=== restore-1b (bf16 latents)" >&2
timeout 2400 python bin/hds_serve_bench --model 1b --restore \
  --prompt-len 128 --batches 1 4 | tee RESTORE_1B.jsonl
echo "=== restore-1b rc=$?" >&2

echo "=== restore-1b (fp8 latents)" >&2
timeout 2400 python bin/hds_serve_bench --model 1b --restore \
  --latent-dtype float8_e4m3fn --prompt-len 128 --batches 1 4 \
  | tee RESTORE_1B_FP8.jsonl
echo "=== restore-1b-fp8 rc=$?" >&2

echo "=== serve7b-int8-fused" >&2
timeout 3300 python bin/hds_serve_bench --model 7b --quantize int8 \
  --max-context 512 --prompt-len 128 --decode-steps 8 --batches 1 \
  --prefill-chunk 64 --fused-decode | tee SERVE_7B_INT8_FUSED.jsonl
echo "=== serve7b-int8-fused rc=$?" >&2

echo "chip_queue4 done" >&2
