#!/usr/bin/env bash
# Round-4 continuation chip queue: remat-policy vets (the 8N->6N
# backward-FLOPs lever for the remat'd 7B-layer and long-context
# configs), 7B fused-decode serving (the 117 ms/step host-driven number
# is mostly tunnel RTT), and the Domino scheduled-HLO overlap test.
# Same artifact-safety rules as chip_session.sh's vet_one.
set -u -o pipefail
cd "$(dirname "$0")/.."

probe() {
  timeout 75 python -c "import jax; d=jax.devices('tpu'); assert d, d" \
    >/dev/null 2>&1
}
probe || { echo "relay DOWN; aborting" >&2; exit 3; }
echo "relay UP" >&2

# rpdots vets now live in the canonical runbook's vet stage (shared
# vet_one with its artifact-safety rules; no duplicated copy here)
bash bin/chip_session.sh vet

echo "=== serve7b-fused" >&2
timeout 3300 python bin/hds_serve_bench --model 7b --max-context 512 \
  --prompt-len 128 --decode-steps 8 --batches 1 --prefill-chunk 64 \
  --fused-decode | tee SERVE_7B_FUSED.jsonl
echo "=== serve7b-fused rc=$?" >&2

echo "=== domino-tpu" >&2
HDS_TPU_TESTS=1 timeout 1800 python -m pytest \
  tests/unit/runtime/test_domino_hlo.py -k TPU -q 2>&1 \
  | tee DOMINO_TPU_r4.log | tail -5
echo "=== domino rc=$?" >&2

echo "chip_queue3 done" >&2
