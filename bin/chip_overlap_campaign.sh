#!/usr/bin/env bash
# ROADMAP item 5 — the chip-truth overlap campaign, as one command.
#
# Runs the full zero-overlap audit suite (native + decomposed-ring +
# hierarchical 2-D mesh + quantized-wire + Domino phases) ON TPU the
# moment the axon relay is up, capturing ZERO_OVERLAP_TPU.jsonl — one
# command refreshes BOTH the flat-ring and hierarchical verdicts.
# Either outcome resolves the COMPONENTS.md Domino contradiction with
# evidence:
#   * native async start/done pairs appear -> XLA schedules overlap for
#     the monolithic collectives after all (record it, close item 5);
#   * native pairs stay 0 -> the decomposed collective-permute chains
#     (flat AND hierarchical rows) in the same capture show the overlap
#     is carried STRUCTURALLY (permute steps with dependence-free dots
#     need no scheduler goodwill) — the fallback The Big Send-off / T3
#     prescribe, with the hierarchical rows adding per-mesh-axis wire
#     bytes and modeled pod-scale wire seconds on real-chip programs.
#
#   bin/chip_overlap_campaign.sh            # probe, then the campaign
#   bin/chip_overlap_campaign.sh --wait     # poll the relay until up
#                                           # (4 min cadence, 12h cap)
#
# Relay-probe guarded like bin/chip_session.sh: a dead relay (or a
# silent CPU fallback) aborts with exit 3 before any phase runs, so
# the committed CPU artifact is never clobbered by a half-dead session.
# ZERO_OVERLAP.jsonl (the CPU capture) is NOT touched by this script.
set -u -o pipefail
cd "$(dirname "$0")/.."
PY="${PYTHON:-python3}"

probe() {
  # jax.devices("tpu") raises on CPU fallback, so a dead relay that
  # silently falls back to CPU still reports DOWN
  timeout 75 "$PY" -c \
    "import jax; d=jax.devices('tpu'); assert len(d) >= 8, d" \
    >/dev/null 2>&1
}

if [ "${1:-}" = "--wait" ]; then
  DEADLINE=$(( $(date +%s) + 43200 ))
  until probe; do
    if [ "$(date +%s)" -ge "$DEADLINE" ]; then
      echo "relay still DOWN after 12h; giving up" >&2
      exit 3
    fi
    echo "relay DOWN $(date -u +%F_%H:%M:%S); retry in 4 min" >&2
    sleep 240
  done
elif ! probe; then
  echo "relay DOWN or CPU fallback (no TPU devices / probe timed out);" \
       "aborting — re-run with --wait to poll" >&2
  exit 3
fi
echo "relay UP; running the overlap campaign on chip" >&2

# the whole audit suite on TPU -> ZERO_OVERLAP_TPU.jsonl. The native
# tier of every audit row is the chip verdict; the perf self-check row
# rides inside the artifact like the CPU capture's does.
timeout 3600 env HDS_ZERO_OVERLAP_PLATFORM=tpu \
  "$PY" bench.py --zero-overlap
rc=$?
echo "campaign rc=$rc" >&2
if [ -f ZERO_OVERLAP_TPU.jsonl ]; then
  "$PY" - <<'EOF'
import json
rows = [json.loads(l) for l in open("ZERO_OVERLAP_TPU.jsonl")]
s = next((r for r in rows if r.get("phase") == "summary"), {})
print("chip verdict: native_async_pairs =", s.get("native_async_pairs"),
      "| structural_overlap_ratio_decomposed =",
      s.get("structural_overlap_ratio_decomposed"),
      "| domino_decomposed_overlapped_pairs =",
      s.get("domino_decomposed_overlapped_pairs"))
print("hierarchical verdict: structural =",
      s.get("hier_structural_overlap_ratio"),
      "| bitwise native/flat/qwire =",
      s.get("hier_bitwise_vs_native"), s.get("hier_bitwise_vs_flat"),
      s.get("hier_qwire_bitwise"),
      "| interaxis wire fraction =",
      s.get("hier_interaxis_wire_fraction"),
      "| pod wire s (inter/intra) =",
      s.get("hier_pod_wire_seconds_inter"),
      s.get("hier_pod_wire_seconds_intra"))
print("calibration leg (MEASURED per-axis GB/s vs declared;",
      "re-prices the pod projection with hardware numbers):",
      "gbps inter/intra =", s.get("wire_cal_gbps_inter"),
      s.get("wire_cal_gbps_intra"),
      "| divergence vs declared =", s.get("wire_cal_divergence_inter"),
      s.get("wire_cal_divergence_intra"))
print("pod-scale legs: unified hpZ bitwise =",
      s.get("hier_hpz_unified_bitwise"),
      "| pipelined bitwise/structural/cross-axis =",
      s.get("hier_pipelined_bitwise"),
      s.get("hier_pipelined_structural_ratio"),
      s.get("hier_pipelined_cross_axis_pairs"),
      "| 16-dev parity =", s.get("hier_16dev_parity"))
print("fused-kernel verdict (the remote-DMA Pallas form only exists",
      "on chip — this block is the ISSUE 18 chip truth):",
      "bitwise plain/qwire =", s.get("fused_parity_plain"),
      s.get("fused_parity_qwire"),
      "| mid-gather leaves =", s.get("fused_mid_gather_leaves"),
      "| in-kernel subsumed pairs fused/unfused =",
      s.get("fused_subsumed_pairs"), s.get("unfused_subsumed_pairs"))
print("  wall-clock: speedup at largest payload =",
      s.get("fused_wallclock_speedup"),
      "| fused <= unfused =", s.get("fused_le_unfused_largest"),
      "| fallbacks =", s.get("fused_fallbacks"),
      "| 3-D mesh gates =", s.get("mesh3d_bookkeeping_ok"),
      "| 16-dev fused parity =", s.get("fused_16dev_parity"))
EOF
  echo "next: commit ZERO_OVERLAP_TPU.jsonl, refresh PERF_TRAJECTORY" \
       "(python -m hcache_deepspeed_tpu.perf index --out" \
       "PERF_TRAJECTORY.json) and update the COMPONENTS.md Domino row;" \
       "fold the measured wire_cal_gbps_* into zero_mesh_link_gbps for" \
       "future declared-model runs" >&2
fi
exit $rc
