#!/usr/bin/env bash
# Round-4 continuation queue 4: end-to-end serving with the head-tiled
# paged-attention kernel (1B fused decode + throughput-latency sweep,
# 7B int8 fused), and slope-based decode diagnostics at 1B and 7B-int8
# (decomposing the 347 ms/step 7B decode).
set -u -o pipefail
cd "$(dirname "$0")/.."

probe() {
  timeout 180 python -c "
import jax, jax.numpy as jnp, random
n = random.randrange(130, 510)
x = jnp.ones((n, 257))
assert jax.devices('tpu')
float(jax.jit(lambda a: (a @ a.T).sum())(x))" >/dev/null 2>&1
}
probe || { echo "relay DOWN; aborting" >&2; exit 3; }
echo "relay UP at $(date -u +%H:%M:%S)" >&2

echo "=== serve 1b fused (new kernel)" >&2
timeout 2400 python bin/hds_serve_bench --model 1b --max-context 512 \
  --prompt-len 128 --decode-steps 32 --batches 1 8 --fused-decode \
  | tee SERVE_1B_FUSED_V2.jsonl
echo "=== serve-1b rc=$?" >&2

echo "=== decode-diag 1b (slope)" >&2
timeout 2400 python bin/hds_decode_diag --model 1b \
  | tee DECODE_DIAG_1B.jsonl
echo "=== diag-1b rc=$?" >&2

echo "=== sweep 1b fused (new kernel)" >&2
timeout 3000 python bin/hds_serve_bench --model 1b --sweep --fused-decode \
  --max-context 512 --prompt-len 128 --max-new 32 --rps 2 4 8 \
  --n-requests 16 --max-batch 8 | tee SWEEP_1B_FUSED_V2.jsonl
echo "=== sweep-1b rc=$?" >&2

echo "=== serve 7b int8 fused (new kernel)" >&2
timeout 3300 python bin/hds_serve_bench --model 7b --quantize int8 \
  --max-context 512 --prompt-len 128 --decode-steps 8 --batches 1 \
  --prefill-chunk 64 --fused-decode | tee SERVE_7B_INT8_FUSED_V2.jsonl
echo "=== serve-7b rc=$?" >&2

echo "=== decode-diag 7b int8 (slope)" >&2
timeout 3300 python bin/hds_decode_diag --model 7b --quantize int8 \
  | tee DECODE_DIAG_7B_INT8.jsonl
echo "=== diag-7b rc=$?" >&2

echo "chip_queue6 done" >&2
