#!/usr/bin/env bash
# Chip-session runbook: run the queued TPU measurements in priority
# order the moment the axon relay is up. Artifacts land in the repo
# root. Safe to re-run; every stage has its own timeout so a relay
# death mid-session still leaves earlier artifacts on disk.
#
#   bin/chip_session.sh            # everything, priority order
#   bin/chip_session.sh bench      # just the BENCH capture
#
# Stages: bench | serve7b | sweep1b | vet | curve | domino
set -u -o pipefail   # pipefail: `stage | tee` must report the stage's rc
cd "$(dirname "$0")/.."
STAGES=${1:-all}

probe() {
  # jax.devices("tpu") raises on CPU fallback, so a dead relay that
  # silently falls back to CPU still reports DOWN
  timeout 75 python -c "import jax; d=jax.devices('tpu'); assert d, d" \
    >/dev/null 2>&1
}

run_stage() {  # name, timeout, cmd...
  local name=$1 tmo=$2; shift 2
  echo "=== [$name] $*" >&2
  timeout "$tmo" "$@"
  local rc=$?
  echo "=== [$name] rc=$rc" >&2
  return $rc
}

if ! probe; then
  echo "relay DOWN or CPU fallback (no TPU devices / probe timed out);" \
       "aborting" >&2
  exit 3
fi
echo "relay UP" >&2

want() { [ "$STAGES" = all ] || [ "$STAGES" = "$1" ]; }

# 1. the round's official perf artifact (winner config first,
#    cache-proven last; error JSON carries last_measured either way)
if want bench; then
  run_stage bench 2000 python bench.py | tee BENCH_LOCAL.json
fi

# 2. 7B serving measurement (FastGen-at-size story). bf16 7B weights
#    are ~13.5 GB — tight on a 16 GB v5e; fall back to int8 weight-only
#    (~7 GB) if the bf16 run dies so the round still gets a 7B number.
if want serve7b; then
  if ! run_stage serve7b 3300 python bin/hds_serve_bench --model 7b \
      --max-context 512 --prompt-len 128 --decode-steps 8 --batches 1 \
      --prefill-chunk 64 | tee SERVE_7B.jsonl; then
    run_stage serve7b-int8 3300 python bin/hds_serve_bench --model 7b \
      --quantize int8 --max-context 512 --prompt-len 128 \
      --decode-steps 8 --batches 1 --prefill-chunk 64 \
      | tee SERVE_7B_INT8.jsonl
  fi
fi

# 3. 1B throughput-latency sweeps: host-driven (continuous batching)
#    and fused (tunnel-valid absolute numbers), plus speculative rows
if want sweep1b; then
  run_stage sweep-host 1800 python bin/hds_serve_bench --model 1b \
    --sweep --rps 0.5 1 2 4 --max-new 32 --n-requests 16 \
    | tee SWEEP_1B_HOST.jsonl
  run_stage sweep-fused 1800 python bin/hds_serve_bench --model 1b \
    --sweep --fused-decode --rps 0.5 1 2 4 --max-new 32 \
    --n-requests 16 | tee SWEEP_1B_FUSED.jsonl
  run_stage lookup 1500 python bin/hds_serve_bench --model 1b \
    --lookup-decode --prompt-len 128 --decode-steps 64 --batches 1 4 \
    | tee LOOKUP_1B.jsonl
fi

# 4. the training MFU curve (11 configs; cold 7B-width compiles can
#    run 700-900s each through the tunnel, so budget for a cold cache —
#    the tool now writes TRAIN_CURVE.json incrementally and never
#    clobbers a good artifact with an all-error run)
if want curve; then
  # inner per-config budget 1500s covers a cold 7B-width compile
  # (700-900s) + 30 timed steps; the outer budget intentionally does
  # NOT cover 11 all-cold configs (16.5ks) — incremental writes keep
  # every completed row if the stage dies first
  run_stage curve 10800 python bin/hds_train_curve --timeout 1500 \
    --out TRAIN_CURVE.json
fi

# 4b. flash-tiling + batch vets of the bench winner. 1300s each: fresh
#     tile-shape compiles through the tunnel exceeded a 700s budget in
#     round 4; none of these configs is server-cache-proven yet.
# each vet: inner watchdog (1200s) < stage timeout (1300s), so a
# wedged compile still emits the error JSON before SIGTERM; tee to a
# .tmp first so a failed re-run can't truncate a prior good artifact
vet_one() {  # name, config
  local out="VET_$1.json"
  HDS_BENCH_CHILD="$2" HDS_BENCH_WATCHDOG_SECS=1200 \
    run_stage "vet-$1" 1300 python bench.py | tail -1 > "$out.tmp"
  if [ ! -s "$out.tmp" ] || { [ -f "$out" ] && ! grep -q '"error"' "$out" \
      && grep -q '"error"' "$out.tmp"; }; then
    # empty result, or an error payload that would clobber a prior
    # good measurement: keep what we have
    rm -f "$out.tmp"
  else
    mv "$out.tmp" "$out"
  fi
  [ -f "$out" ] && cat "$out"
  return 0
}

if want vet; then
  vet_one BLK256 350m-hd128-lchunk-b8-blk256x256
  vet_one BLK512 350m-hd128-lchunk-b8-blk512x1024
  vet_one B16 350m-hd128-b16
  # remat-policy variants (docs/training.md's measured table; first
  # vetted 2026-08-01 18:40-18:47Z — re-runnable from this runbook)
  vet_one RP2K 7b-layer-seq2k-b2-rpdots
  vet_one RP4K 7b-layer-seq4k-b1-rpdots
  vet_one RPS4K 350m-hd128-lchunk-seq4k-b2-rpdots
  vet_one RPS16K 350m-hd128-lchunk-seq16k-b1-rpdots
fi

# 5. Domino scheduled-HLO overlap evidence on real hardware
if want domino; then
  HDS_TPU_TESTS=1 run_stage domino 1200 python -m pytest \
    tests/unit/runtime/test_domino_hlo.py -k TPU -q
fi

echo "chip session done; artifacts: BENCH_LOCAL.json SERVE_7B.jsonl" \
     "SWEEP_1B_{HOST,FUSED}.jsonl LOOKUP_1B.jsonl TRAIN_CURVE.json" \
     "VET_{BLK256,BLK512,B16}.json" >&2
