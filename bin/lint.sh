#!/usr/bin/env bash
# One-shot repo lint: the concurrency & determinism analyzer (which
# folds in `perf lint` as its fourth rule family) plus the standalone
# perf-registry lint for belt-and-braces parity with the tier-1 gate.
#
#   bin/lint.sh            # gate against the committed baseline
#   bin/lint.sh --verbose  # also list sanctioned (pragma'd) sites
#
# Exit: nonzero iff any check fails (new finding, stale baseline
# entry, or schema-less artifact literal).
set -u
cd "$(dirname "$0")/.."

PYTHON=${PYTHON:-python3}
rc=0

echo "== analysis (locks / purity / convention / perf) =="
JAX_PLATFORMS=cpu "$PYTHON" -m hcache_deepspeed_tpu.analysis "$@" \
    || rc=$?

echo "== perf lint =="
JAX_PLATFORMS=cpu "$PYTHON" -m hcache_deepspeed_tpu.perf lint \
    || rc=$?

exit $rc
