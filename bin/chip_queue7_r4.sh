#!/usr/bin/env bash
# Round-4 continuation queue 5: the 7B int8 decode fix attempt — route
# decode matvecs through the fused int8-weight Pallas kernel
# (--quantize fused) instead of dequant-then-matmul, whose measured
# marginal is 253 ms/token; plus the decomposition diags (int8 floors
# now run before the OOM-prone dense floor) and the 1B floor rerun.
set -u -o pipefail
cd "$(dirname "$0")/.."

probe() {
  timeout 180 python -c "
import jax, jax.numpy as jnp, random
n = random.randrange(130, 510)
x = jnp.ones((n, 257))
assert jax.devices('tpu')
float(jax.jit(lambda a: (a @ a.T).sum())(x))" >/dev/null 2>&1
}
probe || { echo "relay DOWN; aborting" >&2; exit 3; }
echo "relay UP at $(date -u +%H:%M:%S)" >&2

echo "=== serve 7b FUSED-int8 fused-decode" >&2
timeout 3300 python bin/hds_serve_bench --model 7b --quantize fused \
  --max-context 512 --prompt-len 128 --decode-steps 8 --batches 1 \
  --prefill-chunk 64 --fused-decode | tee SERVE_7B_QFUSED.jsonl
echo "=== serve-7b-qfused rc=$?" >&2

echo "=== decode-diag 1b (fixed floors)" >&2
timeout 2400 python bin/hds_decode_diag --model 1b --quantize int8 \
  | tee DECODE_DIAG_1B_INT8.jsonl
echo "=== diag-1b rc=$?" >&2

echo "=== decode-diag 7b fused-int8" >&2
timeout 3300 python bin/hds_decode_diag --model 7b --quantize fused \
  | tee DECODE_DIAG_7B_QFUSED.jsonl
echo "=== diag-7b-qfused rc=$?" >&2

echo "chip_queue7 done" >&2
