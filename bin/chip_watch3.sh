#!/usr/bin/env bash
# Relay watcher 3 (round-4 continuation): the multi-group-block_k
# kernel rewrite needs fresh compiles, and the remote compile service
# wedged mid-queue13 (floors landed; stretch/serve hung). Probe with a
# fresh shape every ~5 min; on revival run the rewritten-kernel
# measurement queue, then exit.
set -u -o pipefail
cd "$(dirname "$0")/.."
DEADLINE=$(( $(date +%s) + ${1:-30000} ))

probe() {
  # fresh-shape compile: the compile service is a separate failure
  # domain from execution; a cached-program probe would report UP while
  # every new program hangs
  timeout 180 python -c "
import jax, jax.numpy as jnp, random
n = random.randrange(130, 510)
x = jnp.ones((n, 257))
assert jax.devices('tpu')
float(jax.jit(lambda a: (a @ a.T).sum())(x))" >/dev/null 2>&1
}

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if probe; then
    echo "relay (incl compile service) UP at $(date -u +%H:%M:%S)" >&2

    echo "=== 7b int8 floors, multi-group kernel" >&2
    timeout 2400 python bin/hds_decode_diag --model 7b --quantize fused \
      --floors-only | tee DECODE_DIAG_7B_FLOORS_V3.jsonl
    echo "floors-v3 rc=$?" >&2

    echo "=== 7b fused stretch decomposition" >&2
    timeout 2700 python bin/hds_decode_diag --model 7b --quantize fused \
      --stretch-only | tee DECODE_DIAG_7B_QFUSED_V3.jsonl
    echo "stretch-v3 rc=$?" >&2

    echo "=== serve 7b int8 fused decode e2e" >&2
    timeout 3300 python bin/hds_serve_bench --model 7b --quantize fused \
      --max-context 512 --prompt-len 128 --decode-steps 8 --batches 1 \
      --prefill-chunk 64 --fused-decode | tee SERVE_7B_INT8_FUSED_V3.jsonl
    echo "serve-v3 rc=$?" >&2

    echo "=== 1b fused diag (gate_up no longer fallback)" >&2
    timeout 2400 python bin/hds_decode_diag --model 1b --quantize fused \
      | tee DECODE_DIAG_1B_QFUSED_V2.jsonl
    echo "diag-1b-v2 rc=$?" >&2

    echo "watch3 queue done" >&2
    exit 0
  fi
  sleep 280
done
echo "relay never revived before deadline" >&2
exit 3
